"""Failover routing client for the serve fleet.

``FleetClient`` mirrors the ``KeySet.verify_batch`` surface over a
POOL of workers, with the availability contract the single-process
``VerifyClient`` cannot offer:

    verdicts are always produced, and they are never wrong —
    at worst they are slow.

Mechanics, in the order a batch experiences them:

- **balance**: round-robin over the live endpoints (re-polled from the
  pool per attempt round, so respawned workers join automatically);
- **per-worker deadline**: every attempt is bounded
  (``attempt_timeout``), so a stalled or black-holed worker costs one
  timeout, not the request;
- **integrity**: all verify traffic uses the checksummed CVB1 frame
  pair (types 7/8) — a corrupt frame in EITHER direction is a typed
  transport error (never a verdict), handled like any other failure;
- **hedged retry**: if a response hasn't arrived after ``hedge_after``
  seconds, the SAME batch is also sent to a healthy peer and the first
  answer wins (verdicts are deterministic, so duplicated work is safe
  by construction — verify is idempotent);
- **circuit breaker**: ``breaker_threshold`` consecutive failures open
  a worker's breaker for ``breaker_reset_s`` (one probe re-admits it),
  so a dead worker stops eating attempt timeouts;
- **exponential backoff** between full retry rounds (all endpoints
  tried once), bounded by ``backoff_max``;
- **terminal CPU-oracle fallback**: when every worker is unreachable,
  the batch is verified LOCALLY on ``fallback`` (any object with
  ``verify_batch`` — production: a ``StaticKeySet`` over the same JWKS,
  i.e. the jwt/verify.py oracle the device engines are pinned
  against). Transport failure is therefore never translated into a
  token-level rejection: a token verdict comes from a verify engine or
  the caller gets :class:`FleetExhaustedError` for the whole batch.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from .. import telemetry
from ..errors import CapError
from ..obs import decision as _decision
from ..serve import protocol
from ..serve import vcache as _vcache
from ..serve.client import RemoteVerifyError

Endpoint = Tuple[str, int]


class FleetExhaustedError(CapError):
    default_message = ("no fleet worker reachable and no fallback "
                      "keyset configured")


class _Breaker:
    """Per-endpoint consecutive-failure circuit breaker."""

    __slots__ = ("failures", "open_until", "backoff")

    def __init__(self):
        self.failures = 0
        self.open_until = 0.0
        self.backoff = 0.0


class _Attempt:
    """One in-flight request on its own connection (own socket: an
    abandoned/hedged-out attempt is closed, never reused — CVB1
    correlates by order, so a socket with an unread response is
    poisoned)."""

    def __init__(self, endpoint: Endpoint, timeout: float):
        self.endpoint = endpoint
        self.sock = socket.create_connection(endpoint, timeout=timeout)
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.reader = protocol.FrameReader(self.sock)

    def run(self, tokens: Sequence[str],
            trace: Optional[str] = None) -> List[Any]:
        protocol.send_request(self.sock, tokens, crc=True, trace=trace)
        ftype, entries, echo = self.reader.recv_frame_ex()
        want = (protocol.T_VERIFY_RESP_TRACE if trace is not None
                else protocol.T_VERIFY_RESP_CRC)
        if ftype != want or (trace is not None and echo != trace):
            raise protocol.ProtocolError(
                f"expected checksummed response type {want}, got type "
                f"{ftype}")
        if len(entries) != len(tokens):
            raise protocol.ProtocolError(
                f"response count {len(entries)} != request {len(tokens)}")
        out: List[Any] = []
        import json

        for status, payload in entries:
            if status == 0:
                out.append(json.loads(payload.decode()))
            else:
                out.append(RemoteVerifyError(payload.decode()))
        return out

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class FleetClient:
    """Verify batches against a worker fleet; never wrong, at worst slow.

    endpoints: list of (host, port), dict {id: (host, port)}, a
    callable returning either (the pool's ``endpoints`` method), or a
    ``WorkerPool`` (its ``endpoints`` is used).
    fallback: terminal local keyset (``verify_batch``); optional but
    strongly recommended — without it an all-workers-down batch raises
    :class:`FleetExhaustedError`.
    """

    def __init__(self, endpoints, fallback=None, *,
                 attempt_timeout: float = 5.0,
                 total_deadline: float = 30.0,
                 max_rounds: int = 3,
                 backoff_base: float = 0.05, backoff_max: float = 1.0,
                 breaker_threshold: int = 3, breaker_reset_s: float = 1.0,
                 hedge_after: Optional[float] = None,
                 rr_seed: Optional[int] = None,
                 vcache=None):
        if hasattr(endpoints, "endpoints"):       # a WorkerPool
            self._pool = endpoints
            endpoints = endpoints.endpoints
        else:
            self._pool = None
        # Client-side verdict-cache tier (opt-in): hot tokens short-
        # circuit BEFORE the wire, with the same epoch/exp/nbf clamps
        # as the worker tier. Epoch clamp: pool-backed clients track
        # the pool's push-target epoch per call; bare-endpoint clients
        # (no epoch visibility) get a short hard TTL instead.
        # vcache: None → CAP_CLIENT_VCACHE=1 enables; True → default
        # cache; or pass a configured VerdictCache instance. Bare-
        # endpoint clients have NO epoch visibility, so their only
        # rotation bound is the hard TTL — configurable via
        # CAP_CLIENT_VCACHE_TTL (seconds; default 30, unchanged), and
        # clamped positive so "0" can't mean forever.
        if vcache is None:
            vcache = os.environ.get("CAP_CLIENT_VCACHE", "0") == "1"
        if vcache is True:
            if self._pool is not None:
                ttl = 300.0
            else:
                try:
                    ttl = float(os.environ.get(
                        "CAP_CLIENT_VCACHE_TTL", "30"))
                except ValueError:
                    ttl = 30.0
                ttl = max(0.001, ttl)
            vcache = _vcache.VerdictCache(max_ttl_s=ttl)
        self._vcache: Optional[_vcache.VerdictCache] = \
            vcache if isinstance(vcache, _vcache.VerdictCache) else None
        if self._vcache is not None and self._pool is not None:
            self._vcache.set_epoch(self._pool_epoch())
        self._endpoints_src = endpoints
        self._fallback = fallback
        self._attempt_timeout = attempt_timeout
        self._total_deadline = total_deadline
        self._max_rounds = max_rounds
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max
        self._breaker_threshold = breaker_threshold
        self._breaker_reset_s = breaker_reset_s
        self._hedge_after = hedge_after
        self._lock = threading.Lock()
        self._breakers: Dict[Endpoint, _Breaker] = {}
        # Admission pushback (r20): when a worker answers with
        # ``throttled`` rejects, the retry-after hint opens a
        # client-side pushback window — inside it this client does
        # not hedge (duplicating a throttled batch doubles the very
        # load admission is shedding) and briefly waits before the
        # next routed batch (bounded by backoff_max). A throttled
        # reject is TERMINAL: it never triggers the CPU-oracle
        # fallback (re-verifying shed traffic would defeat admission)
        # and never earns breaker credit or failure — the transport
        # worked; the tenant is over budget.
        self._pushback_until = 0.0
        self._last_retry_after: Optional[float] = None
        # Start round-robin at a per-process offset (rr_seed pins it
        # for tests): N client processes all beginning at index 0
        # march over the workers in lockstep (batching re-syncs the
        # cohort every flush), convoying onto one worker while its
        # peers idle — measured at 1.34× instead of ~2× for 2 workers
        # (PERF.md §Round 7).
        self._rr = (os.getpid() if rr_seed is None else rr_seed) % 7919

    # -- endpoint selection ----------------------------------------------

    def _live_endpoints(self) -> List[Endpoint]:
        src = self._endpoints_src
        eps = src() if callable(src) else src
        if isinstance(eps, dict):
            eps = [eps[k] for k in sorted(eps)]
        return list(eps)

    def _pick(self, exclude: Iterable[Endpoint] = ()) -> Optional[Endpoint]:
        """Next endpoint round-robin, skipping open breakers (a breaker
        past its reset window admits one probe)."""
        eps = [e for e in self._live_endpoints() if e not in set(exclude)]
        if not eps:
            return None
        now = time.monotonic()
        with self._lock:
            for i in range(len(eps)):
                ep = eps[(self._rr + i) % len(eps)]
                br = self._breakers.setdefault(ep, _Breaker())
                if br.open_until <= now:
                    self._rr = (self._rr + i + 1) % len(eps)
                    return ep
        return None

    def has_live_endpoint(self) -> bool:
        """Whether ANY endpoint is currently routable: at least one
        address listed and its breaker closed (or past its reset
        window, i.e. willing to admit a probe). The front-door tier
        uses this as its dead-pool signal for breaker-driven
        re-routes — cheap, lock-held only for the breaker scan."""
        eps = self._live_endpoints()
        if not eps:
            return False
        now = time.monotonic()
        with self._lock:
            return any(
                self._breakers.setdefault(ep, _Breaker()).open_until
                <= now for ep in eps)

    def _on_success(self, ep: Endpoint) -> None:
        with self._lock:
            br = self._breakers.setdefault(ep, _Breaker())
            if br.open_until > time.monotonic():
                # Half-open probe succeeded: the breaker CLOSES (the
                # transition capstat renders alongside the opens).
                telemetry.count("fleet.breaker_closes")
            br.failures = 0
            br.open_until = 0.0
            br.backoff = 0.0
            self._breaker_gauge_locked()

    def _on_failure(self, ep: Endpoint) -> None:
        telemetry.count("fleet.attempt_failures")
        with self._lock:
            br = self._breakers.setdefault(ep, _Breaker())
            br.failures += 1
            if br.failures >= self._breaker_threshold:
                if br.open_until <= time.monotonic():
                    telemetry.count("fleet.breaker_opens")
                br.open_until = time.monotonic() + self._breaker_reset_s
            self._breaker_gauge_locked()

    def _breaker_gauge_locked(self) -> None:
        now = time.monotonic()
        telemetry.gauge("fleet.breakers_open",
                        sum(1 for b in self._breakers.values()
                            if b.open_until > now))

    # -- client-side verdict cache ----------------------------------------

    def _pool_epoch(self) -> Optional[int]:
        try:
            return self._pool.keys_epoch()
        except Exception:  # noqa: BLE001 - cache stays conservative
            return None

    def _cache_consult(self, tokens: List[str]):
        """(hits, miss_idx, fill) — fill(fresh) merges the routed miss
        verdicts into hits IN PLACE and inserts them. None when the
        client tier is off."""
        vc = self._vcache
        if vc is None:
            return None
        if self._pool is not None:
            ep = self._pool_epoch()
            if ep != vc.epoch:
                # a rotation reached the fleet since our last call:
                # cached verdicts from before it die immediately
                vc.bump_epoch(ep)
        hits, miss_idx, digests = vc.lookup_batch(tokens)
        epoch0 = vc.epoch

        def fill(fresh: List[Any]) -> List[Any]:
            vc.insert_batch([digests[i] for i in miss_idx], fresh,
                            tokens=[tokens[i] for i in miss_idx],
                            epoch=epoch0)
            for j, i in enumerate(miss_idx):
                hits[i] = fresh[j]
            return hits

        return hits, miss_idx, fill

    # -- verify ----------------------------------------------------------

    def verify_batch(self, tokens: Sequence[str]) -> List[Any]:
        """Claims dict per verified token; RemoteVerifyError (or the
        fallback's per-token error) per rejected token. Raises only
        :class:`FleetExhaustedError` (whole batch, no fallback).

        When the caller holds a ``telemetry.trace()`` scope, the whole
        submission is spanned (``client.submit``), every attempt /
        hedge / backoff / fallback stage records a span against the
        trace id, and the id crosses the wire in the traced CVB1
        frame pair so the worker's stage spans join the same timeline.
        """
        tokens = list(tokens)
        if not tokens:
            return []
        t0 = time.perf_counter()
        with telemetry.span(telemetry.SPAN_CLIENT_SUBMIT):
            consult = self._cache_consult(tokens)
            if consult is None:
                out = self._verify_batch_routed(
                    tokens, telemetry.current_trace())
            else:
                hits, miss_idx, fill = consult
                if miss_idx:
                    fresh = self._verify_batch_routed(
                        [tokens[i] for i in miss_idx],
                        telemetry.current_trace())
                    out = fill(fresh)
                else:
                    out = hits
        # Router-surface decision records: the verdicts the CALLER
        # sees, whichever path produced them (worker, hedge peer, or
        # the terminal oracle) — worker rejections arrive as
        # RemoteVerifyError and classify back to the engine's reason.
        _decision.record_batch("router", out, tokens=tokens,
                               latency_s=time.perf_counter() - t0)
        self._note_pushback(out)
        return out

    # -- admission pushback ------------------------------------------------

    @staticmethod
    def _is_throttled(res: Any) -> bool:
        return (isinstance(res, Exception)
                and _decision.classify(res)
                == _decision.REASON_THROTTLED)

    def _note_pushback(self, results: Sequence[Any]) -> None:
        """Honor throttled rejects: count them and open the pushback
        window from the worker's retry-after hint."""
        thr = sum(1 for r in results if self._is_throttled(r))
        if not thr:
            return
        telemetry.count("fleet.throttled_tokens", thr)
        hint = None
        for r in results:
            if self._is_throttled(r):
                h = protocol.retry_after_hint(str(r))
                if h is not None and (hint is None or h > hint):
                    hint = h
        if hint is None:
            hint = self._backoff_base
        self._last_retry_after = hint
        until = time.monotonic() + min(hint, self._backoff_max)
        with self._lock:
            if until > self._pushback_until:
                self._pushback_until = until

    def _pushback_remaining(self) -> float:
        with self._lock:
            return max(0.0, self._pushback_until - time.monotonic())

    @classmethod
    def _all_throttled(cls, results: Sequence[Any]) -> bool:
        """True when a response is PURE admission pushback: such an
        exchange proves the transport works but says nothing about
        verify health — it earns neither breaker credit nor failure."""
        return bool(results) and all(cls._is_throttled(r)
                                     for r in results)

    def pushback_state(self) -> Dict[str, Any]:
        """The live pushback window (capstat's router view): seconds
        remaining + the last retry-after hint a worker sent."""
        return {"active_s": round(self._pushback_remaining(), 4),
                "retry_after_s": self._last_retry_after}

    def _verify_batch_routed(self, tokens: List[str],
                             trace: Optional[str]) -> List[Any]:
        # Client-side backoff inside an open pushback window: one
        # bounded wait (≤ backoff_max) before dispatching more load
        # at a fleet that is actively shedding this client's tenants.
        wait = self._pushback_remaining()
        if wait > 0:
            telemetry.count("fleet.pushback_waits")
            with telemetry.span(telemetry.SPAN_ROUTER_BACKOFF):
                time.sleep(min(wait, self._backoff_max))
        deadline = time.monotonic() + self._total_deadline
        tried_this_round: List[Endpoint] = []
        rounds = 0
        while rounds < self._max_rounds and time.monotonic() < deadline:
            ep = self._pick(exclude=tried_this_round)
            if ep is None:
                if not tried_this_round:
                    break              # nothing live at all → fallback
                rounds += 1            # full round exhausted
                tried_this_round = []
                sleep = min(self._backoff_max,
                            self._backoff_base * (2 ** (rounds - 1)))
                telemetry.count("fleet.retry_rounds")
                if time.monotonic() + sleep >= deadline:
                    break
                with telemetry.span(telemetry.SPAN_ROUTER_BACKOFF):
                    time.sleep(sleep)
                continue
            tried_this_round.append(ep)
            budget = min(self._attempt_timeout,
                         deadline - time.monotonic())
            if budget <= 0:
                break
            try:
                # Success credit happens INSIDE the attempt, to the
                # endpoint that actually answered: crediting ``ep``
                # here would reset a stalled primary's breaker on
                # every hedge win, keeping it permanently half-dead.
                return self._attempt_hedged(ep, tokens, budget,
                                            tried_this_round, trace)
            except (OSError, protocol.ProtocolError):
                self._on_failure(ep)
                telemetry.count("fleet.failovers")
        return self._terminal_fallback(tokens, trace)

    def verify_signature(self, token: str) -> Any:
        res = self.verify_batch([token])[0]
        if isinstance(res, Exception):
            raise res
        return res

    # -- internals --------------------------------------------------------

    def _attempt_once(self, ep: Endpoint, tokens: Sequence[str],
                      budget: float,
                      trace: Optional[str] = None,
                      span_name: str = telemetry.SPAN_ROUTER_ATTEMPT
                      ) -> List[Any]:
        t0_wall = time.time()
        t0 = time.perf_counter()
        at = _Attempt(ep, budget)
        try:
            at.sock.settimeout(budget)
            return at.run(tokens, trace=trace)
        finally:
            at.close()
            dur = time.perf_counter() - t0
            telemetry.observe("router.attempt_s", dur)
            if trace:
                # Recorded explicitly: hedge attempts run on worker
                # threads where the caller's context var doesn't flow.
                telemetry.trace_span(trace, span_name, t0_wall, dur,
                                     note=f"{ep[0]}:{ep[1]}")

    def _attempt_hedged(self, ep: Endpoint, tokens: Sequence[str],
                        budget: float, tried: List[Endpoint],
                        trace: Optional[str] = None) -> List[Any]:
        """Primary attempt on ``ep``; if no answer after ``hedge_after``
        and a healthy peer exists, race a duplicate on the peer and
        take the first success (verify is deterministic → duplicate
        execution cannot change any verdict)."""
        hedge = self._hedge_after
        if hedge is not None and self._pushback_remaining() > 0:
            # no hedging inside a pushback window: duplicating a
            # throttled batch doubles exactly the load being shed
            hedge = None
        if hedge is None or hedge >= budget:
            res = self._attempt_once(ep, tokens, budget, trace)
            if not self._all_throttled(res):
                self._on_success(ep)
            return res

        result_q: "List[Tuple[Endpoint, Any]]" = []
        done = threading.Condition()
        attempts: List[_Attempt] = []

        def run_on(endpoint: Endpoint, timeout: float,
                   span_name: str = telemetry.SPAN_ROUTER_ATTEMPT) -> None:
            at = None
            t0_wall = time.time()
            t0a = time.perf_counter()
            try:
                at = _Attempt(endpoint, timeout)
                with done:
                    attempts.append(at)
                at.sock.settimeout(timeout)
                res = at.run(tokens, trace=trace)
                with done:
                    result_q.append((endpoint, res))
                    done.notify_all()
            except (OSError, protocol.ProtocolError) as e:
                if at is not None:
                    at.close()
                self._on_failure(endpoint)
                with done:
                    result_q.append((endpoint, e))
                    done.notify_all()
            finally:
                dur = time.perf_counter() - t0a
                telemetry.observe("router.attempt_s", dur)
                if trace:
                    telemetry.trace_span(
                        trace, span_name, t0_wall, dur,
                        note=f"{endpoint[0]}:{endpoint[1]}")

        t0 = time.monotonic()
        threading.Thread(target=run_on, args=(ep, budget),
                         daemon=True, name="cap-tpu-fleet-attempt").start()
        launched = 1
        hedge_ep = None
        try:
            with done:
                while True:
                    oks = [r for r in result_q
                           if not isinstance(r[1], Exception)]
                    if oks:
                        break
                    if len(result_q) >= launched:
                        # every launched attempt failed
                        raise result_q[0][1]
                    elapsed = time.monotonic() - t0
                    if elapsed >= budget:
                        raise socket.timeout(
                            f"attempt deadline ({budget:.2f}s) exceeded")
                    if (launched == 1 and elapsed >= hedge
                            and hedge_ep is None):
                        hedge_ep = self._pick(exclude=tried)
                        if hedge_ep is not None:
                            tried.append(hedge_ep)
                            telemetry.count("fleet.hedges")
                            remaining = budget - elapsed
                            threading.Thread(
                                target=run_on,
                                args=(hedge_ep, remaining,
                                      telemetry.SPAN_ROUTER_HEDGE),
                                daemon=True,
                                name="cap-tpu-fleet-hedge").start()
                            launched = 2
                    next_wake = (hedge - elapsed if launched == 1
                                 and hedge_ep is None else 0.05)
                    done.wait(timeout=max(0.01, min(next_wake,
                                                    budget - elapsed)))
                winner_ep, res = oks[0]
            if winner_ep != ep:
                telemetry.count("fleet.hedge_wins")
            if not self._all_throttled(res):
                self._on_success(winner_ep)
            return res
        finally:
            # Close EVERY attempt socket (winner included — done with
            # it; losers carry unread or never-coming responses, and a
            # close unblocks their recv so the threads exit).
            with done:
                pending = list(attempts)
            for at in pending:
                at.close()

    def _terminal_fallback(self, tokens: List[str],
                           trace: Optional[str] = None) -> List[Any]:
        if self._fallback is None:
            raise FleetExhaustedError()
        telemetry.count("fleet.fallback_batches")
        telemetry.count("fleet.fallback_tokens", len(tokens))
        # Runs in-caller, so the trace context is still active: any
        # engine spans inside the oracle attach to the same timeline.
        with telemetry.span(telemetry.SPAN_ROUTER_FALLBACK):
            return self._fallback.verify_batch(tokens)

    # -- observability ----------------------------------------------------

    def breaker_states(self) -> Dict[Endpoint, Dict[str, float]]:
        now = time.monotonic()
        with self._lock:
            return {ep: {"failures": br.failures,
                         "open_for_s": max(0.0, br.open_until - now)}
                    for ep, br in self._breakers.items()}

    def key_epoch_skew(self) -> Optional[int]:
        """Key-epoch spread across the pool's workers (0 = converged,
        None when this client routes to bare endpoints): a sustained
        nonzero value means part of the fleet is verifying against
        retired key material — rotation propagation is stuck."""
        if self._pool is None or not hasattr(self._pool, "epoch_skew"):
            return None
        return self._pool.epoch_skew()

    def snapshot(self) -> Dict[str, Any]:
        """Client-side observability bundle for ``tools/capstat.py``:
        the process recorder's mergeable snapshot (router counters,
        attempt latency histograms, breaker gauges) plus the live
        per-endpoint breaker states keyed ``host:port`` and — when the
        client is pool-backed — the fleet's key-epoch map and skew."""
        rec = telemetry.active()
        out = {
            "snapshot": rec.snapshot() if rec is not None else {},
            "spans": rec.trace_spans() if rec is not None else [],
            "breakers": {f"{ep[0]}:{ep[1]}": st
                         for ep, st in self.breaker_states().items()},
            "pushback": self.pushback_state(),
        }
        if rec is not None:
            # router-side tenant fold (issuer-hash keyed): what THIS
            # client routed per tenant, from its own decision counters
            # (docs/OBSERVABILITY.md §Tenant attribution)
            tenants = _decision.tenant_totals(rec.counters(),
                                              surface="router")
            if tenants:
                out["tenants"] = tenants
        if self._vcache is not None:
            out["vcache"] = self._vcache.stats()
        skew = self.key_epoch_skew()
        if skew is not None:
            out["key_epochs"] = {str(k): v for k, v in
                                 self._pool.key_epochs().items()}
            out["epoch_skew"] = skew
            telemetry.gauge("keyplane.epoch_skew", skew)
        out["pushback"] = self.pushback_state()
        if self._pool is not None and hasattr(self._pool,
                                              "resize_events"):
            events = self._pool.resize_events()
            if events:
                out["resize_events"] = events[-8:]
            if hasattr(self._pool, "size"):
                out["pool_size"] = self._pool.size()
        return out

    def close(self) -> None:
        pass                           # attempts own their sockets

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
