"""Fleet worker subprocess entry: ``python -m cap_tpu.fleet.worker_main``.

One process = one :class:`~cap_tpu.serve.worker.VerifyWorker` = one
exclusive device group (the pool passes the placement as environment —
see ``parallel.place.WorkerPlacement.env``). The process:

1. builds its keyset from ``--keyset`` (below), honoring the placement
   env BEFORE any jax backend init;
2. binds the serve socket and prints ONE machine-readable ready line on
   stdout (``CAP_FLEET_READY port=<p> pid=<p>``) — the pool parses it
   to learn the ephemeral port;
3. serves until SIGTERM, then drains gracefully: stops accepting,
   flushes every queued batch, answers the in-flight connections, and
   exits 0 (kill -9 is the CRASH path, exercised by the chaos suite).

Keyset specs (``--keyset``):

- ``stub`` / ``stub:batch_ms=F,token_us=F`` — the deterministic test
  engine (tokens ending ``.ok`` verify). The optional knobs sleep per
  flushed batch / per token to model DEVICE occupancy: ``time.sleep``
  releases the GIL and the "device time" of two worker processes then
  genuinely overlaps, which is exactly the fleet's scaling claim. No
  jax import — stub workers start in ~0.2 s.
- ``jwks:<path>`` — a real ``TPUBatchKeySet`` over the JWKS JSON file
  at ``<path>`` (imports jax + the crypto stack; the placement env
  decides which devices the backend sees).
- ``jwks-url:<url>`` — boot straight from a REMOTE JWKS via the
  keyplane: a ``KeyPlaneKeySet`` fetches the document, builds the
  device tables, and keeps them fresh (jittered periodic refresh +
  singleflight unknown-kid refresh; env knobs
  ``CAP_KEYPLANE_REFRESH_S`` / ``CAP_KEYPLANE_GRACE_S``). Hot key
  rotation without a worker restart — see docs/KEYPLANE.md.
- ``oidc:<issuer>`` — same, with the JWKS URL resolved through OIDC
  discovery (issuer-equality enforced).
- ``oidc-rp:issuer=I;client=C;nonce=N[;algs=ES256+RS256][;aud=a+b]
  [;keyset=<inner spec>]`` — the serve tier's FULL OIDC surface:
  wraps the inner engine (default ``stub:raw=1,echo=1``) in
  ``oidc.OIDCRawKeySet`` so every served token passes signature
  verification AND registered-claims validation (native rules engine
  behind ``CAP_OIDC_NATIVE``; see docs/SERVE.md).
- ``frontdoor:pool=h:p+h:p;pool=h:p[;routing=rr][;spill=2.0]`` — the
  router-tier process: this worker serves CVB1 on the front and
  routes every token to the named worker pools by consistent hash
  over its digest (the native serve chain hands the reader-computed
  sha256[:16] straight through the batcher — no re-hash). KEYS pushes
  to a front-door worker fan out to every pool behind it. See
  docs/SERVE.md §Front door.

Every keyset kind accepts the fleet's KEYS pushes (CVB1 type 11):
``swap_keys`` swaps the live tables and the ready line / STATS /
``/snapshot`` all report ``key_epoch`` so the pool can verify epoch
convergence. The stub records the epoch without changing verdicts —
rotation must never alter a stub fleet's ground truth, which is
exactly what the rotation chaos tests assert.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time


class StubKeySet:
    """Deterministic verdict engine: tokens ending ``.ok`` verify.

    The fleet tests' ground truth — the router's CPU-oracle fallback
    uses the SAME class, so a verdict produced by any path (worker,
    failover peer, fallback) is comparable bit-for-bit.
    """

    def __init__(self, batch_ms: float = 0.0, token_us: float = 0.0,
                 pipeline: float = 0.0, raw: float = 0.0,
                 echo: float = 0.0):
        self._batch_s = batch_ms / 1e3
        self._token_s = token_us / 1e6
        # echo=1 (raw mode only): a verified token's payload is its
        # OWN base64url-decoded middle segment instead of the fixed
        # stub bytes — the crypto-free seam the OIDC serve surface and
        # the claims differential suite drive real claim JSON through
        # (verdict still suffix-determined; undecodable middles keep
        # the fixed payload so the stub can never raise).
        self._echo = bool(echo)
        # raw=1: serve the raw-claims interface real engines expose
        # (verify_batch_raw → payload BYTES per verified token), so a
        # bench against the stub exercises the same zero-reserialize
        # response path as a TPUBatchKeySet. Verdicts stay
        # suffix-determined either way.
        self._raw = bool(raw)
        # pipeline=1: expose verify_batch_async so the batcher runs
        # its 2-deep pipeline against the stub — the simulated device
        # occupancy of batch k+1 then overlaps batch k's drain, the
        # way a real device's H2D/compute overlap does. Opt-in: the
        # chaos suite's timing assumptions stay on the sync path.
        self._pipeline = bool(pipeline)
        self.key_epoch = 0

    def swap_keys(self, jwks, epoch=None, grace_s: float = 0.0) -> int:
        """Keyplane hook: record the pushed epoch. Verdicts stay
        suffix-determined — a rotation must not change the fleet
        tests' ground truth (that WOULD be a wrong verdict)."""
        self.key_epoch = (self.key_epoch + 1 if epoch is None
                          else int(epoch))
        return self.key_epoch

    def _results(self, tokens):
        from ..errors import InvalidSignatureError

        if self._raw:
            reject = InvalidSignatureError(
                "no known key successfully validated the token signature")
            ok = b'{"sub":"stub"}'
            if self._echo:
                return [self._echo_payload(t, ok)
                        if t.endswith(".ok") else reject for t in tokens]
            return [ok if t.endswith(".ok") else reject for t in tokens]
        return [
            {"sub": t} if t.endswith(".ok")
            else InvalidSignatureError(
                "no known key successfully validated the token signature")
            for t in tokens
        ]

    @staticmethod
    def _echo_payload(token: str, default: bytes) -> bytes:
        import base64
        import binascii

        parts = token.split(".")
        if len(parts) != 3:
            return default
        try:
            pad = "=" * (-len(parts[1]) % 4)
            # validate=True: stdlib b64decode silently DROPS foreign
            # characters otherwise, and a corrupt middle segment must
            # keep the fixed payload, not decode to garbage
            return base64.b64decode(
                parts[1].replace("-", "+").replace("_", "/") + pad,
                validate=True)
        except (ValueError, binascii.Error):
            return default

    def verify_batch(self, tokens):
        from ..obs import occupancy as _occupancy

        sleep_s = self._batch_s + self._token_s * len(tokens)
        # The simulated device time is a real dispatch-level busy
        # interval on the occupancy plane — the stubbed-device
        # occupancy baseline (PERF.md §Round 22) comes from here.
        with _occupancy.interval("stub"):
            if sleep_s > 0.0:
                time.sleep(sleep_s)  # models device occupancy (no GIL)
        return self._results(tokens)

    def __getattr__(self, name):
        # Mode-dependent interface: verify_batch_async exists only in
        # pipeline mode (the batcher's hasattr probe picks the right
        # dispatch path) and verify_batch_raw only in raw mode (the
        # worker's raw-claims wrapper probes it the same way).
        # (__dict__ lookup: __getattr__ must not recurse during
        # unpickling, before __init__ has run.)
        if name == "verify_batch_async" and self.__dict__.get("_pipeline"):
            return self._verify_batch_async
        if name == "verify_batch_raw" and self.__dict__.get("_raw"):
            return self.verify_batch
        raise AttributeError(name)

    def _verify_batch_async(self, tokens):
        from ..obs import occupancy as _occupancy

        done_at = time.monotonic() + self._batch_s \
            + self._token_s * len(tokens)
        results = self._results(tokens)
        # pipeline=1 arm: the busy interval spans dispatch → collect
        # return, so two in-flight stub batches overlap on the plane
        # exactly like a real device's H2D/compute overlap (the union
        # accounting never double-counts the overlap window).
        occ_t0 = _occupancy.begin()

        def collect():
            remaining = done_at - time.monotonic()
            if remaining > 0.0:
                time.sleep(remaining)   # occupancy overlaps next prep
            _occupancy.end("stub", occ_t0)
            return results

        return collect


def make_keyset(spec: str):
    """Build the worker's engine from a ``--keyset`` spec string."""
    if spec == "stub" or spec.startswith("stub:"):
        kwargs = {}
        if spec.startswith("stub:"):
            for kv in spec[len("stub:"):].split(","):
                if not kv:
                    continue
                k, _, v = kv.partition("=")
                if k not in ("batch_ms", "token_us", "pipeline", "raw",
                             "echo"):
                    raise ValueError(f"unknown stub option {k!r}")
                kwargs[k] = float(v)
        return StubKeySet(**kwargs)
    if spec.startswith("frontdoor:"):
        # Router-tier process: no device engine of its own — the
        # "keyset" is the digest-affinity router over remote pools.
        from .frontdoor import frontdoor_from_spec

        return frontdoor_from_spec(spec[len("frontdoor:"):])
    if spec.startswith("oidc-rp:"):
        # Full OIDC verify-AND-validate serving: wrap an inner engine
        # spec in the Provider-backed serve surface. Options are
        # ';'-separated k=v; `keyset=` holds the inner spec verbatim
        # (its own ','/':' intact). Discovery is injected, not
        # fetched — an `oidc-rp:` worker boots without IdP traffic.
        from ..oidc.serve_keyset import oidc_rp_keyset_from_spec

        opts = {}
        for part in spec[len("oidc-rp:"):].split(";"):
            if not part:
                continue
            k, _, v = part.partition("=")
            if k not in ("issuer", "client", "nonce", "algs", "aud",
                         "redirect", "keyset"):
                raise ValueError(f"unknown oidc-rp option {k!r}")
            opts[k] = v
        inner = make_keyset(opts.pop("keyset", "stub:raw=1,echo=1"))
        return oidc_rp_keyset_from_spec(opts, inner)
    if spec.startswith("jwks:"):
        _configure_devices()
        import json

        from ..jwt.jwk import parse_jwks
        from ..jwt.tpu_keyset import TPUBatchKeySet

        with open(spec[len("jwks:"):], "r") as f:
            doc = json.load(f)
        return TPUBatchKeySet(parse_jwks(doc))
    if spec.startswith("jwks-url:") or spec.startswith("oidc:"):
        _configure_devices()
        from ..keyplane import source_for_spec
        from ..keyplane.plane import KeyPlaneKeySet

        return KeyPlaneKeySet(
            source_for_spec(spec),
            interval_s=float(os.environ.get(
                "CAP_KEYPLANE_REFRESH_S", "300")),
            grace_s=float(os.environ.get("CAP_KEYPLANE_GRACE_S", "30")))
    raise ValueError(f"unknown keyset spec {spec!r}")


def _configure_devices() -> None:
    """Apply the placement env to jax BEFORE first backend use."""
    n_cpu = int(os.environ.get("CAP_FLEET_CPU_DEVICES", "0") or 0)
    if n_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", n_cpu)
        except AttributeError:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n_cpu}")
    # platform="tpu": TPU_VISIBLE_DEVICES is already in the env and
    # libtpu reads it at backend init — nothing to do here.


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cap_tpu.fleet.worker_main")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--keyset", default="stub")
    ap.add_argument("--target-batch", type=int, default=4096)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--max-batch", type=int, default=32768)
    ap.add_argument("--drain-deadline-s", type=float, default=30.0)
    # Observability server (serve.obs): 0 = ephemeral port (default),
    # -1 = disabled. The bound port is announced on the ready line.
    ap.add_argument("--obs-port", type=int, default=0)
    # Serve chain: "native" (C++ frame I/O + lock-free ring), "python"
    # (reader/responder threads), or "auto" — CAP_SERVE_NATIVE=1 in
    # the environment selects native, anything else python. A native
    # request falls back to python when the library is unbuildable;
    # the ready line's serve_chain= field reports what actually runs.
    ap.add_argument("--serve-chain", default="auto",
                    choices=["auto", "native", "python"])
    # Front-door router chain (frontdoor: keysets only): "native" runs
    # the zero-copy relay gate (C++ readers route by digest against
    # the pushed-down ring and splice payload bytes to the owning
    # pool; Python keeps the slow path), "python" the classic
    # VerifyWorker(FrontDoor) gate, "auto" native unless
    # CAP_FRONTDOOR_NATIVE=0 — an unbuildable native gate falls back
    # to python with frontdoor.native_fallbacks counted. The ready
    # line's frontdoor_chain= field reports what actually runs.
    ap.add_argument("--frontdoor-chain", default="auto",
                    choices=["auto", "native", "python"])
    # Native telemetry plane: "auto" (on whenever the native chain and
    # telemetry are both on — CAP_SERVE_NATIVE_OBS in the environment
    # wins) or "off" (force the Python decision fold; the A/B knob
    # tools/bench_stages.py measures the obs-overhead table with).
    ap.add_argument("--native-obs", default="auto",
                    choices=["auto", "off"])
    # Transport capability: "shm" honors per-connection shared-memory
    # attach negotiations (CVB1 type 15, docs/SERVE.md §Transports) on
    # whichever serve chain runs; "socket" refuses them (counted
    # serve.shm_fallbacks); "auto" defers to CAP_SERVE_TRANSPORT in
    # the environment. The ready line's transport= field reports what
    # actually runs (a stale native library degrades shm → socket).
    ap.add_argument("--transport", default="auto",
                    choices=["auto", "socket", "shm"])
    # Verdict cache: "auto" (on unless CAP_SERVE_VCACHE=0 in the
    # environment) or "off" (force the cache tier — worker cache,
    # native digest handoff, batcher in-flight dedup — off; the
    # graceful-off switch docs/SERVE.md documents).
    ap.add_argument("--vcache", default="auto",
                    choices=["auto", "off"])
    # Crash postmortems: checkpoint telemetry to this path on a timer
    # and on SIGTERM drain, so the pool can collect a ≤interval-stale
    # document even after kill -9. Empty = disabled. The pool passes
    # the path via CAP_FLEET_PM_PATH (env wins over the default).
    ap.add_argument("--postmortem-path",
                    default=os.environ.get("CAP_FLEET_PM_PATH", ""))
    ap.add_argument("--pm-interval", type=float,
                    default=float(os.environ.get(
                        "CAP_FLEET_PM_INTERVAL", "2.0")))
    args = ap.parse_args(argv)

    from .. import telemetry
    from ..serve.worker import VerifyWorker

    # CAP_FLEET_TELEMETRY=0: run with the observability layer OFF
    # (decision accounting is the serve path's main per-token Python
    # cost once the native chain is on — PERF.md §Round 12 quantifies
    # the tradeoff; the STATS op then serves structural fields only).
    if os.environ.get("CAP_FLEET_TELEMETRY", "1") != "0":
        telemetry.enable()           # STATS op serves real numbers
    if args.native_obs == "off":
        os.environ["CAP_SERVE_NATIVE_OBS"] = "0"
    if args.vcache == "off":
        os.environ["CAP_SERVE_VCACHE"] = "0"
    keyset = make_keyset(args.keyset)
    serve_native = (None if args.serve_chain == "auto"
                    else args.serve_chain == "native")
    worker = None
    fd_chain = None
    from .frontdoor import (FrontDoor, NativeFrontDoorServer,
                            native_frontdoor_enabled)

    if isinstance(keyset, FrontDoor):
        want_native = (args.frontdoor_chain == "native"
                       or (args.frontdoor_chain == "auto"
                           and native_frontdoor_enabled()))
        fd_chain = "python"
        if want_native:
            try:
                worker = NativeFrontDoorServer(
                    keyset, host=args.host, port=args.port,
                    obs_port=(None if args.obs_port < 0
                              else args.obs_port))
                fd_chain = "native"
            except Exception as e:  # noqa: BLE001 - fall back loudly
                if args.frontdoor_chain == "native":
                    raise
                keyset._count({"frontdoor.native_fallbacks": 1})
                print(f"CAP_FRONTDOOR_FALLBACK "
                      f"{type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)
    if worker is None:
        worker = VerifyWorker(keyset, host=args.host, port=args.port,
                              target_batch=args.target_batch,
                              max_wait_ms=args.max_wait_ms,
                              max_batch=args.max_batch,
                              obs_port=(None if args.obs_port < 0
                                        else args.obs_port),
                              serve_native=serve_native,
                              transport=(None if args.transport == "auto"
                                         else args.transport))
    pm = None
    if args.postmortem_path:
        from ..obs.postmortem import PostmortemWriter

        pm = PostmortemWriter(args.postmortem_path,
                              interval_s=args.pm_interval,
                              stats_fn=worker.stats)
    host, port = worker.address
    obs = worker.obs_address
    epoch = worker.key_epoch
    # The ONE ready line the pool parses; flushed so it cannot sit in a
    # stdio buffer while the pool's spawn timeout burns. Additive
    # fields (obs=, epoch=) ride the same k=v format the pool already
    # skips when unknown.
    print(f"CAP_FLEET_READY port={port} pid={os.getpid()}"
          + (f" obs={obs[1]}" if obs is not None else "")
          + (f" epoch={epoch}" if epoch is not None else "")
          + f" serve_chain={worker.serve_chain}"
          + f" transport={worker.transport}"
          + f" tel={int(telemetry.active() is not None)}"
          + (f" frontdoor_chain={fd_chain}" if fd_chain else ""),
          flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    # Graceful drain: stop accepting, flush queued batches (bounded),
    # give the responder threads a beat to write the last frames out.
    worker.close(deadline_s=args.drain_deadline_s)
    if pm is not None:
        # Fresh final checkpoint AFTER the drain: the postmortem then
        # reflects everything this process ever served.
        pm.close("sigterm-drain")
    time.sleep(0.2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
