"""Digest-affinity front door: the cross-host routing tier.

Everything below one :class:`~cap_tpu.fleet.pool.WorkerPool` assumes a
single host. The front door is the tier above it: ONE router speaking
CVB1 to N worker pools ("hosts"), turning the per-process verdict
cache (r14's 18.5× on Zipf traffic) into a FLEET-WIDE win:

- **affinity routing**: every token is routed by a consistent hash
  over its sha256[:16] digest — the same digest the C++ serve readers
  compute at frame-parse time (and the vcache keys on), handed down
  through the batcher (``verify_batch_digests``) so the front door
  never re-hashes what the reader already hashed. Every repeat of a
  token therefore lands on the host that cached its verdict; a pool
  joining or leaving remaps ONLY the ring segments it owned.
- **bounded-load spill** (power-of-two-choices): when the hash target
  is hot — its in-flight load exceeds ``spill_factor ×`` the fleet
  average — the token spills to its SECOND ring choice, which then
  warms its own cache for that token. Affinity bends under load, it
  never wedges behind one hot shard.
- **breaker-driven re-route**: a pool with no live workers (crash,
  kill -9, every breaker open) is skipped at partition time, and a
  dispatch that still dies (``FleetExhaustedError``) re-routes to the
  next ring choice before the front door's own terminal CPU-oracle
  fallback. The availability contract is unchanged: never wrong, at
  worst slow.
- **keyplane fan-out**: ``push_keys`` records the distribution target,
  then fans the epoch to every pool (each pool's supervisor keeps
  re-pushing its own stragglers); ``epoch_skew`` / ``key_epochs``
  surface convergence across the WHOLE fleet in one place.

Peer-fill (cache warming for rotated-in workers) rides the CVB1
type-13/14 frame pair and is driven by each pool's supervisor — see
:mod:`cap_tpu.fleet.pool` and docs/SERVE.md §Front door.

Counters (exact: ``frontdoor.lookups == frontdoor.affinity_hits +
frontdoor.affinity_misses``, obs-smoke gates it; misses further split
into spills + reroutes + rr-routed):
``frontdoor.lookups / affinity_hits / affinity_misses / spills /
reroutes / fallback_tokens / keys_pushes``.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

from .. import telemetry
from ..obs import decision as _decision
from ..serve import protocol
from ..serve import vcache as _vcache
from .router import FleetClient, FleetExhaustedError

Endpoint = Tuple[str, int]


class ConsistentHashRing:
    """Consistent hash ring over pool ids, with virtual nodes.

    Positions are sha256-derived 64-bit points, so the keyspace each
    pool owns is stable under membership change: removing a pool
    remaps ONLY its own segments (pinned by test). ``vnodes`` virtual
    nodes per pool keep the ownership split near-uniform.
    """

    def __init__(self, pool_ids: Sequence[int], vnodes: int = 64):
        self._vnodes = vnodes
        self._points: List[int] = []
        self._owners: List[int] = []
        for pid in pool_ids:
            for v in range(vnodes):
                h = hashlib.sha256(
                    f"cap-frontdoor:{pid}:{v}".encode()).digest()
                self._points.append(int.from_bytes(h[:8], "big"))
                self._owners.append(pid)
        order = sorted(range(len(self._points)),
                       key=lambda i: self._points[i])
        self._points = [self._points[i] for i in order]
        self._owners = [self._owners[i] for i in order]
        self._n_pools = len(set(pool_ids))

    def primary(self, digest: bytes) -> int:
        """The pool owning this digest's ring point."""
        return self.preference(digest, 1)[0]

    def preference(self, digest: bytes, n: int = 2) -> List[int]:
        """First ``n`` DISTINCT pools walking the ring clockwise from
        the digest's point — preference order for spill/re-route."""
        pts = self._points
        if not pts:
            raise ValueError("empty ring")
        i = bisect.bisect_right(pts, int.from_bytes(digest[:8], "big"))
        out: List[int] = []
        for k in range(len(pts)):
            owner = self._owners[(i + k) % len(pts)]
            if owner not in out:
                out.append(owner)
                if len(out) >= min(n, self._n_pools):
                    break
        return out


class _PoolArm:
    """One routed pool: its transport client + live-load accounting."""

    def __init__(self, pool_id: int, pool: Any,
                 client: FleetClient):
        self.pool_id = pool_id
        self.pool = pool              # WorkerPool or None (bare eps)
        self.client = client
        self.inflight = 0             # tokens currently dispatched
        self.tokens = 0               # lifetime routed tokens
        self.affinity_hits = 0
        self.spills_in = 0            # tokens spilled TO this arm
        self.reroutes_in = 0          # tokens re-routed TO this arm

    def live(self) -> bool:
        return self.client.has_live_endpoint()


class FrontDoor:
    """Route verify batches across N worker pools by digest affinity.

    pools: a list where each element describes one "host" — a
    ``WorkerPool``, a list of ``(host, port)`` endpoints, or a
    callable returning endpoints (the ``FleetClient`` contract).
    fallback: terminal local keyset (``verify_batch``) used only when
    a token's whole preference chain is exhausted.
    routing: ``"affinity"`` (consistent hash, the point of this tier)
    or ``"rr"`` (round-robin whole batches across pools — the A/B
    control arm tools/bench_serve.py measures against).
    spill_factor: bounded-load constant ``c`` — a primary whose
    in-flight tokens exceed ``c ×`` the fleet-average load spills to
    the second ring choice when that choice is strictly less loaded
    (c=1.25, the classic bounded-load consistent-hashing constant;
    note the average includes the overloaded arm, so with N pools the
    ratio is bounded by N — c must stay below that).
    client_kw: passed through to each pool's ``FleetClient``.
    """

    def __init__(self, pools: Sequence[Any], fallback=None, *,
                 routing: str = "affinity", spill_factor: float = 1.25,
                 vnodes: int = 64,
                 client_kw: Optional[Dict[str, Any]] = None):
        if not pools:
            raise ValueError("front door needs at least one pool")
        if routing not in ("affinity", "rr"):
            raise ValueError(f"unknown routing mode {routing!r}")
        self._routing = routing
        self._spill_factor = float(spill_factor)
        self._fallback = fallback
        kw = dict(client_kw or {})
        kw.setdefault("attempt_timeout", 5.0)
        kw.setdefault("total_deadline", 15.0)
        kw.setdefault("max_rounds", 2)
        self._arms: List[_PoolArm] = []
        for pid, pool in enumerate(pools):
            is_pool = hasattr(pool, "endpoints") \
                and hasattr(pool, "push_keys")
            client = FleetClient(pool, fallback=None,
                                 rr_seed=pid, **kw)
            self._arms.append(_PoolArm(pid, pool if is_pool else None,
                                       client))
        self._ring = ConsistentHashRing(
            [a.pool_id for a in self._arms], vnodes=vnodes)
        self._rr_next = 0
        self._lock = threading.Lock()
        # Keyplane distribution target: recorded BEFORE any pool is
        # contacted (kill -9 mid-push converges via the pools'
        # supervisors; bare-endpoint pools get best-effort re-push on
        # the next push_keys call).
        self._keys_current: Optional[Tuple[int, dict]] = None
        self._ctr = {"frontdoor.lookups": 0,
                     "frontdoor.affinity_hits": 0,
                     "frontdoor.affinity_misses": 0,
                     "frontdoor.spills": 0,
                     "frontdoor.reroutes": 0,
                     "frontdoor.fallback_tokens": 0,
                     "frontdoor.keys_pushes": 0}

    # -- routing ----------------------------------------------------------

    def verify_batch(self, tokens: Sequence[str],
                     digests: Optional[Sequence[Optional[bytes]]]
                     = None) -> List[Any]:
        """Claims per verified token, Exception per rejected — order
        preserved, whatever pool (or the terminal fallback) produced
        each verdict. ``digests``: optional per-token sha256[:16]
        (reader-computed upstream); missing ones are hashed here."""
        tokens = list(tokens)
        if not tokens:
            return []
        t0 = time.perf_counter()
        with telemetry.span(telemetry.SPAN_FRONTDOOR_ROUTE):
            groups, group_hits = self._partition(tokens, digests)
            out: List[Any] = [None] * len(tokens)
            if len(groups) == 1:
                arm_id, idxs = next(iter(groups.items()))
                self._dispatch_group(arm_id, tokens, idxs, out,
                                     group_hits.get(arm_id, 0))
            else:
                threads = []
                for arm_id, idxs in groups.items():
                    th = threading.Thread(
                        target=self._dispatch_group,
                        args=(arm_id, tokens, idxs, out,
                              group_hits.get(arm_id, 0)),
                        daemon=True, name="cap-tpu-frontdoor")
                    th.start()
                    threads.append(th)
                for th in threads:
                    th.join()
        _decision.record_batch("frontdoor", out, tokens=tokens,
                               latency_s=time.perf_counter() - t0)
        # Admission pushback accounting (r20): throttled rejects are
        # TERMINAL here exactly as at the router — the front door
        # never re-routes or oracle-falls-back a shed token (that
        # would defeat admission); it only counts what came back.
        thr = sum(1 for r in out
                  if isinstance(r, Exception)
                  and _decision.classify(r)
                  == _decision.REASON_THROTTLED)
        if thr:
            self._count({"frontdoor.throttled_tokens": thr})
        return out

    def verify_batch_digests(self, tokens: Sequence[str],
                             digests: Optional[Sequence[
                                 Optional[bytes]]]) -> List[Any]:
        """The batcher-facing digest-routed entry point: what lets a
        ``VerifyWorker(FrontDoor(...))`` reuse the native readers'
        frame-parse-time digests instead of re-hashing."""
        return self.verify_batch(tokens, digests=digests)

    def _partition(self, tokens: List[str],
                   digests: Optional[Sequence[Optional[bytes]]]
                   ) -> Tuple[Dict[int, List[int]], Dict[int, int]]:
        """token index → owning arm, by ring + bounded load (or rr);
        also returns how many of each group's tokens were counted as
        affinity hits (dispatch re-routes re-class exactly those)."""
        n = len(tokens)
        arms = self._arms
        if self._routing == "rr" or len(arms) == 1:
            with self._lock:
                arm = arms[self._rr_next % len(arms)]
                self._rr_next += 1
            if not arm.live():
                live = [a for a in arms if a.live()]
                if live:
                    arm = live[self._rr_next % len(live)]
            if len(arms) > 1:
                hit_flags = [self._ring.primary(self._digest(
                    tokens[i], digests, i)) == arm.pool_id
                    for i in range(n)]
                hits = sum(hit_flags)
            else:
                hit_flags = [True] * n
                hits = n
            self._count({"frontdoor.lookups": n,
                         "frontdoor.affinity_hits": hits,
                         "frontdoor.affinity_misses": n - hits})
            self._count_tenants(tokens, hit_flags)
            with self._lock:
                arm.tokens += n
                arm.affinity_hits += hits
            return {arm.pool_id: list(range(n))}, {arm.pool_id: hits}
        # affinity: per-token ring walk with bounded-load spill
        groups: Dict[int, List[int]] = {}
        loads = {a.pool_id: a.inflight for a in arms}
        hits = reroutes = 0
        hit_flags = [False] * n
        hits_by: Dict[int, int] = {}
        spills_by: Dict[int, int] = {}
        reroutes_by: Dict[int, int] = {}
        for i in range(n):
            d = self._digest(tokens[i], digests, i)
            pref = self._ring.preference(d, 2)
            target = pref[0]
            primary_live = arms[target].live()
            if not primary_live and len(pref) > 1:
                # breaker-driven re-route: the hash target is dead
                nxt = next((p for p in pref[1:] if arms[p].live()),
                           None)
                if nxt is not None:
                    target = nxt
                    reroutes += 1
                    reroutes_by[target] = \
                        reroutes_by.get(target, 0) + 1
                else:
                    hits += 1      # nothing live: stay on primary,
                    #                the dispatch fallback owns it
                    hit_flags[i] = True
                    hits_by[target] = hits_by.get(target, 0) + 1
            elif len(pref) > 1:
                avg = (sum(loads.values()) + n) / max(1, len(loads))
                second = pref[1]
                if (loads[target] > self._spill_factor * avg
                        and loads[second] < loads[target]
                        and arms[second].live()):
                    target = second
                    spills_by[target] = spills_by.get(target, 0) + 1
                else:
                    hits += 1
                    hit_flags[i] = True
                    hits_by[target] = hits_by.get(target, 0) + 1
            else:
                hits += 1
                hit_flags[i] = True
                hits_by[target] = hits_by.get(target, 0) + 1
            loads[target] += 1
            groups.setdefault(target, []).append(i)
        spills = sum(spills_by.values())
        self._count({"frontdoor.lookups": n,
                     "frontdoor.affinity_hits": hits,
                     "frontdoor.affinity_misses": spills + reroutes,
                     "frontdoor.spills": spills,
                     "frontdoor.reroutes": reroutes})
        self._count_tenants(tokens, hit_flags)
        with self._lock:
            for a in arms:
                extra = len(groups.get(a.pool_id, ()))
                if extra:
                    a.tokens += extra
                a.affinity_hits += hits_by.get(a.pool_id, 0)
                a.spills_in += spills_by.get(a.pool_id, 0)
                a.reroutes_in += reroutes_by.get(a.pool_id, 0)
        return groups, hits_by

    def _count_tenants(self, tokens: List[str],
                       hit_flags: List[bool]) -> None:
        """Per-tenant routed traffic + affinity hit-rate
        (``frontdoor.tenant.<t>.lookups`` / ``.affinity_hits``) — the
        router-side tenant fold capstat's ledger aggregates across
        pools. Labels come from the same header-segment cache the
        decision fold uses (one dict hit per token)."""
        from collections import Counter

        labels = _decision.tenant_labels(tokens)
        lookups = Counter(labels)
        hit_c = Counter(t for t, h in zip(labels, hit_flags) if h)
        inc = {}
        for t, k in lookups.items():
            inc[f"frontdoor.tenant.{t}.lookups"] = k
        for t, k in hit_c.items():
            inc[f"frontdoor.tenant.{t}.affinity_hits"] = k
        self._count(inc)

    @staticmethod
    def _digest(token: str, digests, i: int) -> bytes:
        if digests is not None:
            d = digests[i]
            if d:
                return d
        return _vcache.token_digest(token)

    def _dispatch_group(self, arm_id: int, tokens: List[str],
                        idxs: List[int], out: List[Any],
                        hits0: int = 0) -> None:
        """One arm's sub-batch: primary arm → ring re-route chain →
        terminal fallback. Writes verdicts into ``out`` in place
        (disjoint index sets per group — no lock needed)."""
        sub = [tokens[i] for i in idxs]
        tried = set()
        chain = [arm_id] + [a.pool_id for a in self._arms
                            if a.pool_id != arm_id]
        results: Optional[List[Any]] = None
        for hop, pid in enumerate(chain):
            if pid in tried:
                continue
            tried.add(pid)
            arm = self._arms[pid]
            if hop > 0:
                if not arm.live():
                    continue
                # A dispatch-time death discovered AFTER partition
                # accounting: re-class exactly the tokens the
                # partition counted as hits, so the
                # lookups == hits + misses invariant stays exact.
                self._count({"frontdoor.reroutes": len(sub),
                             "frontdoor.affinity_misses": hits0,
                             "frontdoor.affinity_hits": -hits0})
                hits0 = 0
                with self._lock:
                    arm.reroutes_in += len(sub)
            with self._lock:
                arm.inflight += len(sub)
            try:
                results = arm.client.verify_batch(sub)
                break
            except (FleetExhaustedError, OSError,
                    protocol.ProtocolError):
                results = None
            finally:
                with self._lock:
                    arm.inflight -= len(sub)
        if results is None:
            results = self._terminal_fallback(sub)
        for j, i in enumerate(idxs):
            out[i] = results[j]

    def _terminal_fallback(self, tokens: List[str]) -> List[Any]:
        if self._fallback is None:
            raise FleetExhaustedError()
        self._count({"frontdoor.fallback_tokens": len(tokens)})
        with telemetry.span(telemetry.SPAN_ROUTER_FALLBACK):
            return self._fallback.verify_batch(tokens)

    # -- keyplane fan-out -------------------------------------------------

    def push_keys(self, jwks_doc: dict, epoch: Optional[int] = None
                  ) -> Dict[int, Any]:
        """Fan one key epoch out to every pool; returns
        pool_id → per-worker ack map (or per-endpoint list for bare
        endpoints). The target is recorded BEFORE any pool is
        contacted, so a front door asked again (or a pool supervisor)
        can converge stragglers — kill -9 mid-push is the chaos suite's
        bread and butter."""
        with self._lock:
            if epoch is None:
                epoch = (self._keys_current[0] + 1
                         if self._keys_current else 1)
            epoch = int(epoch)
            self._keys_current = (epoch, jwks_doc)
        self._count({"frontdoor.keys_pushes": 1})
        telemetry.gauge("keyplane.epoch", epoch)
        out: Dict[int, Any] = {}
        for arm in self._arms:
            if arm.pool is not None:
                out[arm.pool_id] = arm.pool.push_keys(jwks_doc,
                                                      epoch=epoch)
            else:
                out[arm.pool_id] = self._push_keys_endpoints(
                    arm, jwks_doc, epoch)
        return out

    def _push_keys_endpoints(self, arm: _PoolArm, jwks_doc: dict,
                             epoch: int) -> Dict[str, Optional[int]]:
        """Direct KEYS push to a bare-endpoint pool (no supervisor —
        best effort, re-converged on the next push)."""
        import json as _json
        import socket as _socket

        acked: Dict[str, Optional[int]] = {}
        for ep in arm.client._live_endpoints():
            key = f"{ep[0]}:{ep[1]}"
            try:
                with _socket.create_connection(ep, timeout=5.0) as s:
                    s.settimeout(30.0)
                    protocol.send_keys_push(s, jwks_doc, epoch)
                    ftype, entries = \
                        protocol.FrameReader(s).recv_frame()
                if (ftype == protocol.T_KEYS_ACK and entries
                        and entries[0][0] == 0):
                    acked[key] = int(
                        _json.loads(entries[0][1]).get("epoch"))
                else:
                    acked[key] = None
            except (OSError, protocol.ProtocolError, ValueError,
                    TypeError):
                acked[key] = None
        return acked

    def swap_keys(self, jwks_doc: dict, epoch: Optional[int] = None,
                  grace_s: float = 0.0) -> int:
        """The engine-facing alias: lets a front door BE a
        ``VerifyWorker`` keyset, so a KEYS push to the front-door
        server propagates to every pool behind it."""
        with self._lock:
            if epoch is None:
                epoch = (self._keys_current[0] + 1
                         if self._keys_current else 1)
        self.push_keys(jwks_doc, epoch=int(epoch))
        return int(epoch)

    @property
    def key_epoch(self) -> Optional[int]:
        """The epoch the fleet is converging on (None: never pushed)."""
        with self._lock:
            return self._keys_current[0] if self._keys_current else None

    def key_epochs(self) -> Dict[str, Optional[int]]:
        """``"p<pool>.w<worker>"`` → last known epoch, every pool."""
        out: Dict[str, Optional[int]] = {}
        for arm in self._arms:
            if arm.pool is None:
                continue
            for wid, ep in arm.pool.key_epochs().items():
                out[f"p{arm.pool_id}.w{wid}"] = ep
        return out

    def epoch_skew(self) -> int:
        """Spread between newest and oldest worker epoch across the
        WHOLE fleet (0 = converged) — rotation health in one number,
        which capstat renders CONVERGED/SKEW."""
        epochs = [e for e in self.key_epochs().values()
                  if e is not None]
        skew = (max(epochs) - min(epochs)) if epochs else 0
        telemetry.gauge("keyplane.epoch_skew", skew)
        return skew

    # -- observability ----------------------------------------------------

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._ctr)

    def _count(self, inc: Dict[str, int]) -> None:
        inc = {k: v for k, v in inc.items() if v}
        if not inc:
            return
        with self._lock:
            for k, v in inc.items():
                self._ctr[k] = self._ctr.get(k, 0) + v
        rec = telemetry.active()
        if rec is not None:
            rec.count_many(inc)

    def snapshot(self) -> Dict[str, Any]:
        """The capstat-facing bundle (``capstat --frontdoor FILE``):
        routing counters, per-pool affinity/spill/load state, breaker
        views, and the fleet epoch map + skew."""
        with self._lock:
            pools = {
                str(a.pool_id): {
                    "tokens": a.tokens,
                    "affinity_hits": a.affinity_hits,
                    "spills_in": a.spills_in,
                    "reroutes_in": a.reroutes_in,
                    "inflight": a.inflight,
                    "endpoints": len(a.client._live_endpoints()),
                    "live": a.live(),
                    "pushback": a.client.pushback_state(),
                } for a in self._arms
            }
            ctr = dict(self._ctr)
        skew = self.epoch_skew()
        # per-tenant routed-traffic view (issuer-hash keyed — raw
        # issuers never appear anywhere in this document)
        tenants: Dict[str, Dict[str, int]] = {}
        for k, v in ctr.items():
            if not k.startswith("frontdoor.tenant."):
                continue
            parts = k.split(".")
            if len(parts) != 4:
                continue
            tenants.setdefault(parts[2], {})[parts[3]] = int(v)
        return {
            "routing": self._routing,
            "counters": ctr,
            "tenants": tenants,
            "pools": pools,
            "key_epochs": self.key_epochs(),
            "epoch_skew": skew,
            "epoch": self.key_epoch,
            "breakers": {
                str(a.pool_id): {f"{ep[0]}:{ep[1]}": st
                                 for ep, st in
                                 a.client.breaker_states().items()}
                for a in self._arms
            },
        }

    def close(self) -> None:
        for a in self._arms:
            a.client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def frontdoor_from_spec(spec: str) -> FrontDoor:
    """Build a front door from a ``--keyset frontdoor:`` spec string:

        frontdoor:pool=h1:p1+h2:p2;pool=h3:p3[;routing=rr][;spill=2.0]

    Pools are ``;``-separated ``pool=`` items, each a ``+``-separated
    list of host:port endpoints; ``routing`` and ``spill`` map to the
    constructor knobs. The resulting worker serves CVB1 on the front
    AND speaks CVB1 to every pool behind — the deployable router-tier
    process (docs/SERVE.md §Front door).
    """
    pools: List[List[Endpoint]] = []
    routing = "affinity"
    spill = 1.25
    for part in spec.split(";"):
        if not part:
            continue
        k, _, v = part.partition("=")
        if k == "pool":
            eps: List[Endpoint] = []
            for hp in v.split("+"):
                host, _, port = hp.rpartition(":")
                eps.append((host, int(port)))
            if not eps:
                raise ValueError("empty pool in frontdoor spec")
            pools.append(eps)
        elif k == "routing":
            routing = v
        elif k == "spill":
            spill = float(v)
        else:
            raise ValueError(f"unknown frontdoor option {k!r}")
    if not pools:
        raise ValueError("frontdoor spec names no pools")
    return FrontDoor(pools, routing=routing, spill_factor=spill)


# ---------------------------------------------------------------------------
# Native relay front door (r21): the C++ fast path wrapped around the
# FrontDoor slow path.
# ---------------------------------------------------------------------------

def native_frontdoor_enabled() -> bool:
    """The kill switch: ``CAP_FRONTDOOR_NATIVE=0`` forces the Python
    router chain everywhere the native relay would be picked by
    default (worker_main ``--frontdoor-chain auto``)."""
    import os
    return os.environ.get("CAP_FRONTDOOR_NATIVE", "1").lower() \
        not in ("0", "false", "no", "off")


# drain meta[1] reason codes → counter suffixes
# (frontdoor_native.cpp R_* enum; pinned by the layout handshake's
# version field rather than per-code — keep in sync)
_SLOW_REASONS = {1: "control", 2: "dead_pool", 3: "overload",
                 4: "upstream_fail", 5: "unrouted"}

# cap_frontdoor_counter slot → exported counter suffix, in slot order
# (native_serve.FDC_* constants)
_FDC_NAMES = ("conns", "frames", "tokens", "proto_errors", "pongs",
              "lookups", "hits", "relays", "relay_tokens", "splices",
              "slow_frames", "slow_tokens", "upstream_fails",
              "seq_held_max", "dropped_posts", "conns_closed")


class NativeFrontDoorServer:
    """The zero-copy relay front door: C++ per-connection readers
    parse/validate/classify each CVB1 frame ONCE at the edge, look the
    reader-computed digest up against a pushed-down ring snapshot, and
    splice payload bytes straight onto the owning pool's socket —
    responses splice back in strict per-connection seq order. Python
    (the wrapped :class:`FrontDoor`) stays the slow path: bounded-load
    spill, breaker re-route, keyplane fan-out and every control frame
    drain through ``cap_frontdoor_drain`` and are answered via
    ``cap_frontdoor_post_raw`` — the twin pattern (drr.py) keeps the
    routing decision itself pinned bit-exact via
    ``cap_frontdoor_probe_route``.

    Surface-compatible with ``VerifyWorker(FrontDoor(...))`` — the
    deployable gateway worker_main builds for ``--frontdoor-chain
    native``: ``address`` / ``obs_address`` / ``key_epoch`` /
    ``stats()`` / ``close()``.

    Counting contract: the native fast path relays ONLY to a token's
    live primary owner, so its lookups and affinity hits are EQUAL by
    construction; the refresh thread folds their deltas into the
    wrapped front door's counters, and every slow-path token is
    counted by ``FrontDoor.verify_batch`` itself — the fleet-wide
    ``frontdoor.lookups == affinity_hits + affinity_misses`` invariant
    survives the split (obs-smoke gates it through this chain).

    Known undercount: per-POOL ``tokens`` / ``affinity_hits`` arm
    attribution only sees slow-path traffic (the native relay keeps
    per-pool in-flight gauges, not lifetime arm counters) — the
    fleet-level counters above are exact either way.
    """

    def __init__(self, frontdoor: FrontDoor, host: str = "127.0.0.1",
                 port: int = 0, *, obs_port: Optional[int] = None,
                 drain_wait_s: float = 0.1, refresh_s: float = 0.25,
                 max_frames: int = 64):
        import ctypes
        import socket as _socket

        import numpy as np

        from ..serve import native_serve as _ns

        lib = _ns.load()
        if not getattr(lib, "cap_fd_ok", False):
            raise ImportError(
                "native front-door symbols unavailable (stale "
                "libcapruntime.so — run: make native-build)")
        if frontdoor._routing != "affinity":
            raise ValueError(
                "native relay requires routing='affinity' (rr is the "
                "Python control arm)")
        if len(frontdoor._arms) > _ns.FD_MAX_POOLS:
            raise ValueError(
                f"native relay supports at most {_ns.FD_MAX_POOLS} "
                f"pools, got {len(frontdoor._arms)}")
        self._fd = frontdoor
        self._ns = _ns
        self._np = np
        self._lib = lib
        self._ct = ctypes
        self._u8p = ctypes.POINTER(ctypes.c_uint8)
        self._u64p = ctypes.POINTER(ctypes.c_uint64)
        self._i32p = ctypes.POINTER(ctypes.c_int32)
        self._i64p = ctypes.POINTER(ctypes.c_int64)
        self._drain_wait_s = float(drain_wait_s)
        self._refresh_s = float(refresh_s)
        self._max_frames = int(max_frames)
        self._closed = False
        self._stop_ev = threading.Event()
        self._ctr_lock = threading.Lock()
        self._last_lookups = 0
        self._last_hits = 0
        self._ep_sig: Optional[tuple] = None
        self._h = ctypes.c_void_p(lib.cap_frontdoor_create())
        try:
            self._push_config(force=True)
            for arm in frontdoor._arms:
                lib.cap_frontdoor_set_live(
                    self._h, arm.pool_id, 1 if arm.live() else 0)
            self._sock = _socket.socket(_socket.AF_INET,
                                        _socket.SOCK_STREAM)
            self._sock.setsockopt(_socket.SOL_SOCKET,
                                  _socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            self._sock.listen(128)
            self._addr: Endpoint = self._sock.getsockname()
        except Exception:
            lib.cap_frontdoor_destroy(self._h)
            raise
        self._obs = None
        if obs_port is not None:
            from ..serve.obs import ObsServer

            self._obs = ObsServer(host=host, port=obs_port,
                                  extra=self._obs_gauges,
                                  snapshot_extra=self._obs_snapshot)
        self._threads = []
        for name, fn in (("cap-tpu-fd-accept", self._accept_loop),
                         ("cap-tpu-fd-drain", self._drain_loop),
                         ("cap-tpu-fd-refresh", self._refresh_loop)):
            th = threading.Thread(target=fn, daemon=True, name=name)
            th.start()
            self._threads.append(th)

    # -- VerifyWorker-compatible surface ----------------------------------

    @property
    def address(self) -> Endpoint:
        return self._addr

    @property
    def obs_address(self) -> Optional[Endpoint]:
        return self._obs.address if self._obs is not None else None

    @property
    def key_epoch(self) -> Optional[int]:
        return self._fd.key_epoch

    @property
    def serve_chain(self) -> str:
        return "native"

    @property
    def frontdoor_chain(self) -> str:
        return "native"

    @property
    def transport(self) -> str:
        return "socket"

    @property
    def frontdoor(self) -> FrontDoor:
        return self._fd

    def native_counters(self) -> Dict[str, int]:
        """Raw relay counters, exported as ``frontdoor.native.<slot>``
        (``seq_held_max`` is a high-water mark, not a monotone count)."""
        lib, h = self._lib, self._h
        return {f"frontdoor.native.{name}":
                int(lib.cap_frontdoor_counter(h, i))
                for i, name in enumerate(_FDC_NAMES)}

    def probe_route(self, digests: Sequence[bytes]) -> List[int]:
        """The parity pin: the exact owner decision the relay fast
        path would make per 16-byte digest (-1 = slow path)."""
        np = self._np
        if not digests:
            return []
        buf = np.frombuffer(
            b"".join(bytes(d[:16]).ljust(16, b"\x00")
                     for d in digests), np.uint8)
        out = np.zeros(len(digests), np.int32)
        self._lib.cap_frontdoor_probe_route(
            self._h, buf.ctypes.data_as(self._u8p), len(digests),
            out.ctypes.data_as(self._i32p))
        return [int(x) for x in out]

    def stats(self) -> dict:
        import os as _os

        rec = telemetry.active()
        obs = self.obs_address
        self._fold_native_counters()
        return {
            "pid": _os.getpid(),
            "key_epoch": self.key_epoch,
            "serve_chain": self.serve_chain,
            "frontdoor_chain": self.frontdoor_chain,
            "transport": self.transport,
            "obs_port": obs[1] if obs is not None else None,
            "counters": {**(rec.counters() if rec is not None else {}),
                         **self._fd.counters(),
                         **self.native_counters()},
            "series": rec.summary() if rec is not None else {},
            "snapshot": rec.snapshot() if rec is not None else {},
            "frontdoor": self._fd.snapshot(),
        }

    def close(self, deadline_s: float = 30.0) -> None:
        import socket as _socket

        self._closed = True
        self._stop_ev.set()
        if self._obs is not None:
            self._obs.close()
        # shutdown() is what actually WAKES an accept() blocked in the
        # accept thread (closing the fd from another thread leaves it
        # parked until a client happens to connect — close would then
        # burn its whole deadline in the join below).
        try:
            self._sock.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # The drain thread must be OUT of cap_frontdoor_drain before
        # the handle dies (destroy frees it); it exits on its next
        # empty poll once _closed is set.
        deadline = time.monotonic() + max(1.0, deadline_s)
        for th in self._threads:
            th.join(timeout=max(0.1, deadline - time.monotonic()))
        if not any(th.is_alive() for th in self._threads):
            self._lib.cap_frontdoor_destroy(self._h)
        # else: leak the handle rather than free it under a live
        # drain call — close is on the exit path either way.
        self._fd.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- config push-down -------------------------------------------------

    def _push_config(self, force: bool = False) -> None:
        """Stage ring points + per-pool endpoints and commit one
        immutable config snapshot. The ring is static for a front
        door's lifetime (membership is fixed at construction); the
        endpoint lists are not — the refresh thread re-commits when a
        pool's live endpoint set changes."""
        fd, lib, np = self._fd, self._lib, self._np
        sig = tuple(tuple(sorted(a.client._live_endpoints()))
                    for a in fd._arms)
        if not force and sig == self._ep_sig:
            return
        self._ep_sig = sig
        ring = fd._ring
        pts = np.asarray(ring._points, dtype=np.uint64)
        owners = np.asarray(ring._owners, dtype=np.int32)
        rc = lib.cap_frontdoor_stage_ring(
            self._h, pts.ctypes.data_as(self._u64p),
            owners.ctypes.data_as(self._i32p), len(pts))
        if rc:
            raise ValueError("ring owner id out of native range")
        for arm, eps in zip(fd._arms, sig):
            for ep_host, ep_port in eps:
                # (path, 0) is the UDS convention fleet-wide; the
                # native side takes port<0 as "host is a UDS path".
                lib.cap_frontdoor_stage_pool(
                    self._h, arm.pool_id, ep_host.encode(),
                    ep_port if ep_port > 0 else -1)
        lib.cap_frontdoor_commit(
            self._h, len(fd._arms),
            self._ct.c_double(fd._spill_factor))

    # -- observability ----------------------------------------------------

    def _obs_gauges(self) -> Dict[str, float]:
        lib, h, ns = self._lib, self._h, self._ns
        conns = int(lib.cap_frontdoor_counter(h, ns.FDC_CONNS)) \
            - int(lib.cap_frontdoor_counter(h, ns.FDC_CONNS_CLOSED))
        g = {"frontdoor.native.active": 1.0,
             "frontdoor.native.conns_live": float(conns),
             "frontdoor.native.seq_held_max": float(
                 lib.cap_frontdoor_counter(h, ns.FDC_SEQ_HELD_MAX))}
        for arm in self._fd._arms:
            g[f"frontdoor.pool.{arm.pool_id}.relay_inflight"] = float(
                lib.cap_frontdoor_inflight(h, arm.pool_id))
        return g

    def _obs_snapshot(self) -> Optional[dict]:
        self._fold_native_counters()
        return {"v": 1, "counters": self.native_counters(),
                "gauges": {}, "series": {}}

    def _fold_native_counters(self) -> None:
        """Fold native fast-path lookup/hit deltas into the wrapped
        front door's exact counters (relays go only to live primaries:
        the two deltas are equal, misses stay 0 for native traffic)."""
        lib, h, ns = self._lib, self._h, self._ns
        with self._ctr_lock:
            cur_l = int(lib.cap_frontdoor_counter(h, ns.FDC_LOOKUPS))
            cur_h = int(lib.cap_frontdoor_counter(h, ns.FDC_HITS))
            d_l, d_h = cur_l - self._last_lookups, \
                cur_h - self._last_hits
            self._last_lookups, self._last_hits = cur_l, cur_h
        if d_l or d_h:
            self._fd._count({"frontdoor.lookups": d_l,
                             "frontdoor.affinity_hits": d_h})

    # -- threads ----------------------------------------------------------

    def _accept_loop(self) -> None:
        import os as _os
        import socket as _socket

        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listen socket closed
            telemetry.count("worker.connections")
            try:
                conn.setsockopt(_socket.IPPROTO_TCP,
                                _socket.TCP_NODELAY, 1)
            except OSError:
                pass
            fd = conn.detach()
            if self._closed:        # raced close(): never touch the
                _os.close(fd)       # handle once destroy may run
                return
            cid = int(self._lib.cap_frontdoor_add_conn(self._h, fd))
            if cid < 0:
                _os.close(fd)

    def _refresh_loop(self) -> None:
        fd, lib = self._fd, self._lib
        while not self._stop_ev.wait(self._refresh_s):
            try:
                for arm in fd._arms:
                    lib.cap_frontdoor_set_live(
                        self._h, arm.pool_id, 1 if arm.live() else 0)
                self._push_config()
                self._fold_native_counters()
            except Exception:  # noqa: BLE001 - keep refreshing
                pass

    def _drain_loop(self) -> None:
        np, lib, ct = self._np, self._lib, self._ct
        mf = self._max_frames
        blob = np.zeros(1 << 20, np.uint8)
        frame_off = np.zeros(mf + 1, np.int64)
        meta = np.zeros(mf * 4, np.int32)
        seqs = np.zeros(mf, np.int64)
        need = np.zeros(1, np.int64)
        while True:
            n = int(lib.cap_frontdoor_drain(
                self._h, ct.c_double(self._drain_wait_s),
                blob.ctypes.data_as(self._u8p), blob.size,
                frame_off.ctypes.data_as(self._i64p),
                meta.ctypes.data_as(self._i32p),
                seqs.ctypes.data_as(self._i64p), mf,
                need.ctypes.data_as(self._i64p)))
            if n == -1:
                return
            if n == -2:     # grow-and-retry; the frame is carried
                blob = np.zeros(max(int(need[0]), blob.size * 2),
                                np.uint8)
                continue
            for k in range(n):
                raw = bytes(blob[int(frame_off[k]):
                                 int(frame_off[k + 1])])
                conn_id = int(meta[4 * k + 0])
                reason = int(meta[4 * k + 1])
                ftype = int(meta[4 * k + 2])
                ntok = int(meta[4 * k + 3])
                rname = _SLOW_REASONS.get(reason, f"r{reason}")
                self._fd._count(
                    {f"frontdoor.native.slow.{rname}": 1})
                try:
                    resp = self._handle_slow(raw, ftype, ntok)
                except Exception as e:  # noqa: BLE001 - must answer
                    resp = protocol.encode_response(
                        [e] * max(1, ntok),
                        crc=ftype == protocol.T_VERIFY_REQ_CRC)
                rb = np.frombuffer(resp, np.uint8)
                lib.cap_frontdoor_post_raw(
                    self._h, conn_id, int(seqs[k]),
                    rb.ctypes.data_as(self._u8p), len(resp))
            if n == 0 and self._closed:
                return

    # -- the slow path ----------------------------------------------------

    def _handle_slow(self, raw: bytes, ftype: int, ntok: int) -> bytes:
        """One drained frame → exactly one pre-encoded response frame.
        Every branch returns bytes (the caller's catch-all answers
        anything that raises) — a slow-path frame is NEVER dropped."""
        import json as _json

        P = protocol
        if ftype in (P.T_VERIFY_REQ, P.T_VERIFY_REQ_CRC,
                     P.T_VERIFY_REQ_TRACE):
            _ft, tokens, trace, _c = P.parse_frame_bytes(raw)
            try:
                with telemetry.span(telemetry.SPAN_FRONTDOOR_RELAY):
                    results = self._fd.verify_batch(tokens)
            except Exception as e:  # noqa: BLE001 - per-token errors
                results = [e] * len(tokens)
            return P.encode_response(
                results, crc=ftype == P.T_VERIFY_REQ_CRC, trace=trace)
        if ftype == P.T_STATS_REQ:
            return P.encode_stats_response(self.stats())
        if ftype == P.T_KEYS_PUSH:
            try:
                _ft, entries, _t, _c = P.parse_frame_bytes(raw)
                doc = _json.loads(entries[0])
                epoch = self._fd.swap_keys(doc["jwks"],
                                           epoch=doc.get("epoch"))
                return P.encode_keys_ack(epoch=epoch)
            except Exception as e:  # noqa: BLE001 - error ack
                return P.encode_keys_ack(
                    error=f"{type(e).__name__}: {e}")
        if ftype == P.T_PEER_FILL:
            return P.encode_peer_ack(
                error="TypeError: front-door relay keeps no verdict "
                      "cache (peer fill targets pool workers)")
        if ftype == P.T_SHM_ATTACH:
            return P.encode_shm_ack(
                error="shm transport is not offered at the front door")
        raise protocol.MalformedFrameError(
            f"unroutable slow-path frame type {ftype}")
