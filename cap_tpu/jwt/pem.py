"""PEM public-key parsing (parity with jwt/keyset.go:178-200).

Accepts a PKIX ``PUBLIC KEY`` block or an x509 ``CERTIFICATE`` block and
returns the contained RSA / ECDSA / Ed25519 public key.
"""

from __future__ import annotations

from cryptography import x509
from cryptography.hazmat.primitives.asymmetric import ec, ed25519, rsa
from cryptography.hazmat.primitives.serialization import load_pem_public_key

from ..errors import InvalidParameterError

PublicKey = object  # rsa.RSAPublicKey | ec.EllipticCurvePublicKey | ed25519.Ed25519PublicKey


def parse_public_key_pem(pem: str | bytes) -> PublicKey:
    """Parse a PEM-encoded public key or certificate into a public key."""
    if isinstance(pem, str):
        pem = pem.encode("utf-8")
    if b"CERTIFICATE" in pem:
        try:
            cert = x509.load_pem_x509_certificate(pem)
        except ValueError as e:
            raise InvalidParameterError(f"failed to parse certificate: {e}") from e
        key = cert.public_key()
    else:
        try:
            key = load_pem_public_key(pem)
        except (ValueError, TypeError) as e:
            raise InvalidParameterError(f"failed to parse public key PEM: {e}") from e
    if not isinstance(
        key, (rsa.RSAPublicKey, ec.EllipticCurvePublicKey, ed25519.Ed25519PublicKey)
    ):
        raise InvalidParameterError(
            "unsupported public key type (want RSA, ECDSA, or Ed25519)"
        )
    return key
