"""Single-token signature verification on CPU.

This is the correctness oracle and default execution path — the analog
of the reference's go-jose → Go stdlib crypto pipeline
(jwt/keyset.go:126-139,154-173 → crypto/{rsa,ecdsa,ed25519}). The TPU
batch engine (cap_tpu/tpu) must match it bit-for-bit, on failures as
well as successes.

Dependency posture: the classical families (RS*/PS*/ES*/EdDSA over
OpenSSL-backed keys) import the ``cryptography`` package at call time.
The ML-DSA family and ``HostECPublicKey``-backed ES* keys verify on
pure-integer host oracles (``tpu.mldsa.py_verify``,
``tpu.ec._py_verify_one``'s math) and therefore work on crypto-less
hosts — the availability contract the crypto-free KAT sweeps and the
hybrid-migration chaos suite rely on.
"""

from __future__ import annotations

import hashlib

from ..errors import InvalidSignatureError, UnsupportedAlgError
from . import algs
from .jose import ParsedJWS

# ES* algorithms pin both the curve and the raw signature coordinate size
# (RFC 7518 §3.4): ES256→P-256/32B, ES384→P-384/48B, ES512→P-521/66B.
_EC_CURVE_FOR_ALG = {
    algs.ES256: ("secp256r1", 32),
    algs.ES384: ("secp384r1", 48),
    algs.ES512: ("secp521r1", 66),
}
_EC_JOSE_CRV_FOR_ALG = {
    algs.ES256: "P-256", algs.ES384: "P-384", algs.ES512: "P-521",
}


def _hash_cls(alg: str):
    from cryptography.hazmat.primitives import hashes

    return {"sha256": hashes.SHA256, "sha384": hashes.SHA384,
            "sha512": hashes.SHA512}[algs.HASH_FOR_ALG[alg]]


def key_matches_alg(key, alg: str) -> bool:
    """Whether the key type is usable with the given JOSE alg."""
    if alg in algs.PQ_ALGORITHMS:
        # AKP families (ML-DSA, SLH-DSA): the alg name IS the
        # parameter-set name the key object carries.
        return getattr(key, "parameter_set", None) == alg
    host_crv = getattr(key, "curve_name", None)
    if host_crv is not None:                  # HostECPublicKey
        return _EC_JOSE_CRV_FOR_ALG.get(alg) == host_crv
    try:
        from cryptography.hazmat.primitives.asymmetric import (
            ec,
            ed25519,
            rsa,
        )
    except ImportError:
        return False
    if alg in (algs.RS256, algs.RS384, algs.RS512,
               algs.PS256, algs.PS384, algs.PS512):
        return isinstance(key, rsa.RSAPublicKey)
    if alg in _EC_CURVE_FOR_ALG:
        return (
            isinstance(key, ec.EllipticCurvePublicKey)
            and key.curve.name == _EC_CURVE_FOR_ALG[alg][0]
        )
    if alg == algs.EdDSA:
        return isinstance(key, ed25519.Ed25519PublicKey)
    return False


def _verify_host_ec(parsed: ParsedJWS, key) -> None:
    """Pure-integer ECDSA for HostECPublicKey (SEC1 §4.1.4) — the same
    acceptance rule as Go crypto/ecdsa and OpenSSL."""
    from ..tpu.ec import curve, py_ecdsa_verify

    _, coord = _EC_CURVE_FOR_ALG[parsed.alg]
    sig = parsed.signature
    if len(sig) != 2 * coord:
        raise InvalidSignatureError(
            f"bad ECDSA signature length {len(sig)} for {parsed.alg}")
    digest = hashlib.new(algs.HASH_FOR_ALG[parsed.alg],
                         parsed.signing_input).digest()
    cp = curve(key.curve_name)
    nums = key.public_numbers()
    if not py_ecdsa_verify(cp, nums.x, nums.y, sig, digest):
        raise InvalidSignatureError("signature verification failed")


def verify_parsed(parsed: ParsedJWS, key) -> None:
    """Verify ``parsed.signature`` over ``parsed.signing_input`` with ``key``.

    Raises InvalidSignatureError on any mismatch (wrong key, tampered
    content, malformed signature encoding, wrong curve/key type).
    """
    alg = parsed.alg
    if alg not in algs.SUPPORTED_ALGORITHMS:
        raise UnsupportedAlgError(f"unsupported signing algorithm {alg!r}")
    if not key_matches_alg(key, alg):
        raise InvalidSignatureError(f"key type does not match alg {alg}")

    if alg in algs.MLDSA_ALGORITHMS:
        from ..tpu.mldsa import py_verify

        # py_verify subsumes every encoding rule (length, hint
        # validity, z range) — all rejects are signature-layer rejects,
        # matching the raw-r||s gates of the ES* branch below.
        if not py_verify(key, parsed.signature, parsed.signing_input):
            raise InvalidSignatureError("signature verification failed")
        return
    if alg in algs.SLHDSA_ALGORITHMS:
        from ..tpu.slhdsa import py_verify as slh_py_verify

        # SLH-DSA's only non-root reject gate is the signature
        # length; everything else lands in the hash-root compare —
        # all rejects are signature-layer, like ML-DSA's.
        if not slh_py_verify(key, parsed.signature,
                             parsed.signing_input):
            raise InvalidSignatureError("signature verification failed")
        return
    if getattr(key, "curve_name", None) is not None:
        return _verify_host_ec(parsed, key)

    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric import ec, padding
    from cryptography.hazmat.primitives.asymmetric.utils import (
        encode_dss_signature,
    )

    try:
        if alg in (algs.RS256, algs.RS384, algs.RS512):
            key.verify(
                parsed.signature, parsed.signing_input,
                padding.PKCS1v15(), _hash_cls(alg)(),
            )
        elif alg in (algs.PS256, algs.PS384, algs.PS512):
            h = _hash_cls(alg)
            # Verify with AUTO salt-length recovery: the reference's
            # rsa.VerifyPSS path accepts any salt length, and real-world
            # signers commonly use max-length salts.
            key.verify(
                parsed.signature, parsed.signing_input,
                padding.PSS(mgf=padding.MGF1(h()), salt_length=padding.PSS.AUTO),
                h(),
            )
        elif alg in _EC_CURVE_FOR_ALG:
            _, coord = _EC_CURVE_FOR_ALG[alg]
            sig = parsed.signature
            if len(sig) != 2 * coord:
                raise InvalidSignatureError(
                    f"bad ECDSA signature length {len(sig)} for {alg}"
                )
            r = int.from_bytes(sig[:coord], "big")
            s = int.from_bytes(sig[coord:], "big")
            key.verify(
                encode_dss_signature(r, s), parsed.signing_input,
                ec.ECDSA(_hash_cls(alg)()),
            )
        else:  # EdDSA
            key.verify(parsed.signature, parsed.signing_input)
    except InvalidSignature as e:
        raise InvalidSignatureError("signature verification failed") from e
    except ValueError as e:
        raise InvalidSignatureError(f"signature verification failed: {e}") from e
