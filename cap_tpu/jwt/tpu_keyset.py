"""TPUBatchKeySet — the accelerated KeySet implementation.

The north-star component (BASELINE.json): cap's per-token verify hot
path lifted into ``verify_batch(tokens)``, dispatched to the JAX/TPU
engine in cap_tpu/tpu. Gated behind the same ``KeySet`` interface as
the CPU implementations, so the Validator and the OIDC Provider share
one accelerated path while pure-CPU stays the default.

Pipeline per batch:
1. host prep (C++ runtime when built, Python fallback): JOSE split,
   base64url decode, header alg/kid scan, SHA-2 of the signing input;
2. kid → key-table row resolution (the "key gather" axis);
3. bucket by (family, hash): one static-shape device dispatch per
   bucket, padded to power-of-two sizes to bound XLA recompilation;
4. RS*/PS* → batched Montgomery modexp; ES*/EdDSA → batched EC kernels
   (curve tables); anything unbucketable falls back to the CPU oracle;
5. per-token verdicts: claims dict or the taxonomy error — identical
   outcomes to the CPU path, on failures as well as successes.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .. import telemetry
from ..errors import (
    InvalidParameterError,
    InvalidSignatureError,
    MalformedTokenError,
    NilParameterError,
)
from ..obs import decision as _decision
from ..obs import occupancy as _occupancy
from . import algs
from .jose import ParsedJWS, is_json_form, parse_jws
from .jwk import JWK
from .keyset import KeySet
from .verify import key_matches_alg, verify_parsed

_RS = {algs.RS256: "sha256", algs.RS384: "sha384", algs.RS512: "sha512"}
_PS = {algs.PS256: "sha256", algs.PS384: "sha384", algs.PS512: "sha512"}
_ES = {algs.ES256: "P-256", algs.ES384: "P-384", algs.ES512: "P-521"}

_MIN_BUCKET = 128
N_COEFF = 256                 # ML-DSA ring degree (FIPS 204)

# RSA key-table rows encode as class * _RSA_CLS_STRIDE + row. The
# stride must exceed any realistic per-class key count: with a 256
# stride, key 256 of class 0 would alias to class 1 row 0 and dispatch
# against the wrong modulus table (a persistent false rejection).
_RSA_CLS_STRIDE = 1 << 16


def _pad_size(n: int, max_chunk: int) -> int:
    """Next power of two ≥ n (≥ _MIN_BUCKET), capped at max_chunk."""
    size = _MIN_BUCKET
    while size < n:
        size *= 2
    return min(size, max_chunk)


def _mldsa_alg_indices(pb, ok: np.ndarray, name: str) -> np.ndarray:
    """Token indices whose protected alg is the ML-DSA set ``name``.

    The native prep only interns the ten classical alg names
    (``ALG_NAMES``); everything else carries ``alg_id == -1`` plus the
    raw alg bytes — so the ML-DSA bucket match is a vectorized compare
    against ``alg_raw``, no per-token Python parsing.
    """
    nb = np.frombuffer(name.encode(), np.uint8)
    cand = ok & (pb.alg_id == -1) & (pb.alg_len == len(nb))
    if not cand.any():
        return np.zeros(0, np.int64)
    match = (pb.alg_raw[:, : len(nb)] == nb).all(axis=1)
    return np.nonzero(cand & match)[0]


def _pad_telemetry(family: str, m: int, pad: int) -> None:
    """Per-family dispatch-lane accounting: how many device lanes each
    chunk used (``pad``, the padded bucket size) and how many of them
    were WASTE (padding rows verifying zeros). The fill-ratio
    histogram plus the waste counter let the exposition surface show,
    per family, how much device work the bucket rounding costs — the
    per-stage occupancy attribution the FPGA/GPU engines in PAPERS.md
    report, measured instead of assumed."""
    telemetry.observe(f"device.{family}.lanes", pad)
    telemetry.observe(f"device.{family}.fill_ratio", m / pad if pad else 0.0)
    if pad > m:
        telemetry.count(f"device.{family}.pad_waste_rows", pad - m)
    telemetry.gauge(f"device.{family}.last_lanes", pad)


def _pack_rsa_record(pb, table, kind: str, hash_name: str,
                     chunk: np.ndarray, crows: np.ndarray,
                     pad: int) -> np.ndarray:
    """One packed RS*/PS* record matrix for ``chunk`` (native packer
    when built, numpy fallback otherwise). Shared by the dispatch path
    and the resident engine benchmark so both measure the same bytes."""
    from ..tpu import rsa as tpursa

    h_len = tpursa.HASH_LEN[hash_name]
    width = 2 * table.k
    m = len(chunk)
    sizes_all = np.asarray(table.sizes_bytes, np.int64)
    sizes = sizes_all[crows]
    if kind == "rs":
        # PKCS#1 v1.5 needs emLen ≥ tLen + 11; the PSS equivalent
        # checks run on device.
        t_len = len(tpursa.DIGEST_INFO_PREFIX[hash_name]) + h_len
        extra = (sizes >= t_len + 11).astype(np.uint8)
    else:
        extra = np.ones(m, np.uint8)
    rec = pb.pack_sig_records(chunk, sizes, extra, crows, width,
                              h_len, pad)
    if rec is None:               # pre-packer .so: numpy path
        sig_mat = np.zeros((pad, width), np.uint8)
        sig_mat[:m] = pb.sig_matrix(chunk, width)
        sig_lens = np.zeros(pad, np.int64)
        sig_lens[:m] = pb.sig_len[chunk]
        hash_mat = np.zeros((pad, 64), np.uint8)
        hash_mat[:m] = pb.digest[chunk]
        key_idx = np.zeros(pad, np.int32)
        key_idx[:m] = crows
        rec = tpursa.rs_packed_records(table, sig_mat, sig_lens,
                                       hash_mat, hash_name, key_idx)
        if kind == "ps":
            # rs_packed_records applies the v1.5 emLen flag; PSS
            # keeps plain length validity.
            len_ok = (sig_lens == sizes_all[
                np.concatenate([crows, np.zeros(pad - m, np.int32)])])
            rec[:, width + h_len] = len_ok.astype(np.uint8)
            rec[m:, width + h_len] = 0
    return rec


def _pack_es_record(pb, table, chunk: np.ndarray, crows: np.ndarray,
                    hash_len: int, pad: int) -> np.ndarray:
    """One packed ES* record matrix for ``chunk`` (native packer when
    built, numpy fallback otherwise)."""
    from ..tpu import ec as tpuec

    cb = table.curve.coord_bytes
    width = 2 * cb
    m = len(chunk)
    rec = pb.pack_sig_records(chunk, np.full(m, width, np.int64),
                              np.ones(m, np.uint8), crows, width,
                              hash_len, pad)
    if rec is None:               # pre-packer .so: numpy path
        sig_mat = np.zeros((pad, width), np.uint8)
        sig_mat[:m] = pb.sig_matrix(chunk, width)
        sig_lens = np.zeros(pad, np.int64)
        sig_lens[:m] = pb.sig_len[chunk]
        hash_mat = np.zeros((pad, 64), np.uint8)
        hash_mat[:m] = pb.digest[chunk]
        key_idx = np.zeros(pad, np.int32)
        key_idx[:m] = crows
        rec = tpuec.es_packed_records(table, sig_mat, sig_lens,
                                      hash_mat, hash_len, key_idx)
    return rec


def resident_dispatchers(ks: "TPUBatchKeySet", tokens: Sequence[str],
                         repeat: int = 1, records_out: Optional[list] = None):
    """Device-RESIDENT dispatch closures for the engine benchmark.

    Preps + packs ``tokens`` ONCE, places every packed family record on
    the device, and returns ``(n_tokens, [fn, ...])`` where each ``fn()``
    re-dispatches the full packed verify program (record unpack, limb
    build, modexp / EC ladder, verdict reduce) on the already-resident
    record and returns a device array of per-slot accept bits summed to
    a scalar. Nothing host-side — prep, packing, H2D — happens on the
    timed path, so slope-timing these closures measures ENGINE speed
    independent of link bandwidth (bench.py ``resident_mixed_vps``;
    the reference's whole verify hot path is keyset.go:126-139).

    Every token must route to a packed family (RS*/PS*/ES*/EdDSA with
    device tables and known kids) — anything that would fall back to
    the CPU oracle raises, so the resident number can never silently
    measure a subset.

    ``repeat``: tile every packed record ``repeat``× along the batch
    axis before placing it on device. Dispatching a repeat-R set does
    R× the device work in the SAME number of dispatches — the slope
    between a repeat-1 and a repeat-(1+R) run cancels per-dispatch
    host/tunnel overhead exactly (resident_slope_vps scaled mode).
    The advertised token count stays the base n; accept sums are
    checked against repeat·n.

    ``records_out``: optional list the placed device records are
    appended to — bench.py's mesh mode reads their
    ``addressable_shards`` to publish the ACTUAL per-device shard
    sizes rather than the intended n/N split.
    """
    import jax.numpy as jnp

    from ..runtime.native_binding import ALG_NAMES, prepare_batch_arrays
    from ..tpu import ec as tpuec
    from ..tpu import ed25519 as tpued
    from ..tpu import rsa as tpursa

    pb = prepare_batch_arrays(list(tokens))
    if not (pb.status == 0).all():
        raise InvalidParameterError(
            "resident bench tokens must all prep cleanly")
    alg_ids = {name: i for i, name in enumerate(ALG_NAMES)}
    covered = np.zeros(pb.n, bool)
    fns = []

    def occ_fn(fam: str, fn):
        """Each resident closure is an engine dispatch site: its
        re-dispatch records a per-family busy interval into the
        occupancy plane (no-op while telemetry is off, so the timed
        bench path is untouched)."""
        def dispatch_fn():
            with _occupancy.interval(fam):
                return fn()
        return dispatch_fn

    def dev_put(rec):
        import jax

        if repeat > 1:
            rec = np.tile(rec, (repeat,) + (1,) * (rec.ndim - 1))
        if ks._mesh is not None:
            # Place SHARDED up front: the verify fns' own shard_batch
            # then sees the target sharding and is a no-op, keeping
            # the timed path free of cross-device copies.
            from ..parallel.place import shard_batch

            rec = shard_batch(ks._mesh, rec)
        else:
            rec = jax.device_put(rec)
        if records_out is not None:
            records_out.append(rec)
        return rec

    for alg_name, hash_name in list(_RS.items()) + list(_PS.items()):
        kind = "rs" if alg_name in _RS else "ps"
        idx = np.nonzero(pb.alg_id == alg_ids[alg_name])[0]
        if len(idx) == 0:
            continue
        rows = pb.kid_rows(idx, ks._kid_rsa_row)
        if ks._n_rsa_keys == 1:
            rows = np.where(rows == -1, 0, rows)
        if (rows < 0).any():
            raise InvalidParameterError(
                f"{alg_name}: tokens with unknown kid")
        covered[idx] = True
        for cls, table in enumerate(ks._rsa_tables):
            sel = (rows // _RSA_CLS_STRIDE) == cls
            if not sel.any():
                continue
            if len(table.n_ints) > 255:   # u8 kid row, arrays path
                raise InvalidParameterError(
                    f"{alg_name}: >255 keys in one size class is "
                    "outside the packed path")
            chunk = idx[sel]
            crows = (rows[sel] % _RSA_CLS_STRIDE).astype(np.int32)
            pad = _pad_size(len(chunk), ks._max_chunk)
            if len(chunk) > pad:
                raise InvalidParameterError("bucket exceeds max_chunk")
            rec = dev_put(_pack_rsa_record(pb, table, kind, hash_name,
                                           chunk, crows, pad))
            verify = (tpursa.verify_rs_packed_pending if kind == "rs"
                      else tpursa.verify_ps_packed_pending)

            def fn(rec=rec, table=table, hash_name=hash_name,
                   verify=verify):
                # device_put inside is a no-op: rec is already resident
                return jnp.sum(verify(table, rec, hash_name,
                                      mesh=ks._mesh).astype(jnp.int32))

            fns.append((len(chunk), occ_fn("rsa", fn)))

    for alg_name, crv in _ES.items():
        idx = np.nonzero(pb.alg_id == alg_ids[alg_name])[0]
        if len(idx) == 0:
            continue
        if crv not in ks._ec_tables:
            raise InvalidParameterError(f"no {crv} device table")
        table = ks._ec_tables[crv]
        if len(table.keys) > 255:         # u8 kid row, arrays path
            raise InvalidParameterError(
                f"{alg_name}: >255 keys is outside the packed path")
        rows = pb.kid_rows(idx, ks._kid_ec_row[crv])
        if len(table.keys) == 1:
            rows = np.where(rows == -1, 0, rows)
        if (rows < 0).any():
            raise InvalidParameterError(
                f"{alg_name}: tokens with unknown kid")
        covered[idx] = True
        hash_len = tpursa.HASH_LEN[algs.HASH_FOR_ALG[alg_name]]
        pad = _pad_size(len(idx), ks._max_chunk)
        if len(idx) > pad:
            raise InvalidParameterError("bucket exceeds max_chunk")
        rec = dev_put(_pack_es_record(pb, table, idx,
                                      rows.astype(np.int32),
                                      hash_len, pad))

        def fn(rec=rec, table=table, hash_len=hash_len):
            # deg slots are CPU-re-verified on the real path, so they
            # count as accepts here (deg is flags-masked: padded slots
            # contribute nothing). The OR also keeps the deg output
            # live so XLA cannot dead-code any of the ladder.
            ok_dev, deg_dev = tpuec.verify_es_packed_pending(
                table, rec, hash_len, mesh=ks._mesh,
                ladder=ks._ec_ladder)
            return jnp.sum((ok_dev | deg_dev).astype(jnp.int32))

        fns.append((len(idx), occ_fn("ec", fn)))

    idx = np.nonzero(pb.alg_id == alg_ids[algs.EdDSA])[0]
    if len(idx) > 0:
        table = ks._ed_table
        if table is None:
            raise InvalidParameterError("no EdDSA device table")
        if len(table.keys) > 255:         # u8 kid row, arrays path
            raise InvalidParameterError(
                "EdDSA: >255 keys is outside the packed path")
        rows = pb.kid_rows(idx, ks._kid_ed_row)
        if len(table.keys) == 1:
            rows = np.where(rows == -1, 0, rows)
        if (rows < 0).any():
            raise InvalidParameterError("EdDSA: tokens with unknown kid")
        covered[idx] = True
        pad = _pad_size(len(idx), ks._max_chunk)
        if len(idx) > pad:
            raise InvalidParameterError("bucket exceeds max_chunk")
        sigs = [pb.signature(int(j)) for j in idx]
        msgs = [pb.signing_input(int(j)) for j in idx]
        fill = pad - len(idx)
        key_idx = np.concatenate([rows.astype(np.int32),
                                  np.zeros(fill, np.int32)])
        rec = dev_put(tpued.ed_packed_records(
            table, sigs + [b""] * fill, msgs + [b""] * fill, key_idx))

        def fn(rec=rec, table=table):
            return jnp.sum(tpued.verify_ed_packed_pending(
                table, rec, mesh=ks._mesh).astype(jnp.int32))

        fns.append((len(idx), occ_fn("ed", fn)))

    for pset in sorted(getattr(ks._tables, "mldsa_tables", {})):
        from ..tpu import mldsa as tpumldsa

        table = ks._tables.mldsa_tables[pset]
        idx = _mldsa_alg_indices(pb, pb.status == 0, pset)
        if len(idx) == 0:
            continue
        rows = pb.kid_rows(idx, ks._kid_mldsa_row[pset])
        if len(table.keys) == 1:
            rows = np.where(rows == -1, 0, rows)
        if (rows < 0).any():
            raise InvalidParameterError(
                f"{pset}: tokens with unknown kid")
        covered[idx] = True
        pad = _pad_size(len(idx), ks._max_chunk)
        if len(idx) > pad:
            raise InvalidParameterError("bucket exceeds max_chunk")
        sigs = [pb.signature(int(j)) for j in idx]
        msgs = [pb.signing_input(int(j)) for j in idx]
        if tpumldsa.fused_enabled():
            # Fused arm: the WHOLE single-round-trip program (Keccak
            # μ/c̃ + SampleInBall + NTT network + w1Encode + compare)
            # re-dispatches on resident lanes; the accept-bit sum IS
            # the integrity check, exactly like the classical
            # families (the verdict is computed on-device).
            fprep = tpumldsa._FusedPrep(table, sigs, msgs,
                                        rows.astype(np.int32), pad)
            if not fprep.valid[: len(idx)].all():
                raise InvalidParameterError(
                    f"{pset}: resident bench tokens must decode "
                    "cleanly")
            pair = tpumldsa._W1_PAD.get(pset)
            if pair is None:
                pair = tpumldsa._W1_PAD[pset] = \
                    tpumldsa._w1_pad_lanes(table.params)
            import jax

            devs = [dev_put(a) for a in
                    (fprep.mu_blocks, fprep.mu_nblk, fprep.ct_block,
                     fprep.ct_cmp, fprep.z, fprep.h, fprep.key_idx,
                     fprep.valid)]
            # constant pad tensor: never tiled/sharded (not batched)
            w1p = jax.device_put(pair[1])
            p = table.params

            def fn(devs=devs, w1p=w1p, table=table, p=p,
                   tpumldsa=tpumldsa):
                ok, _exh = tpumldsa._fused_jit()(
                    table.a_mont, table.t1_mont, *devs, w1p,
                    p.gamma2, p.tau, p.w1_bits)
                return jnp.sum(ok.astype(jnp.int32))

            fns.append((len(idx), occ_fn("mldsa", fn)))
            continue
        prep = tpumldsa._PreppedChunk(table, sigs, msgs,
                                      rows.astype(np.int32), pad)
        if not prep.valid[: len(idx)].all():
            raise InvalidParameterError(
                f"{pset}: resident bench tokens must decode cleanly")
        # The accept bit needs the host-side μ/c̃ SHAKE compare, which
        # must stay OFF the timed path — so the resident program
        # instead matches the engine's w1 lanes against the pure-int
        # oracle's, per token, ON DEVICE. Oracle-accept is asserted
        # here once; a broken engine then mismatches lanes and the
        # slope harness's accept-sum check fails exactly as for the
        # classical families.
        expected = tpumldsa.host_w1(table, prep).astype(np.uint8)
        ok_host = prep.finalize(table, expected)
        if not ok_host[: len(idx)].all():
            raise InvalidParameterError(
                f"{pset}: resident bench tokens must all verify")
        live = np.zeros(pad, np.uint8)
        live[: len(idx)] = 1
        zd = dev_put(prep.z)
        cd = dev_put(prep.c)
        hd = dev_put(prep.h)
        kd = dev_put(prep.key_idx)
        ed = dev_put(expected)
        md = dev_put(live)

        def fn(zd=zd, cd=cd, hd=hd, kd=kd, ed=ed, md=md, table=table,
               tpumldsa=tpumldsa):
            w1 = tpumldsa.w1_resident(table, zd, cd, hd, kd)
            eq = jnp.all(w1 == ed, axis=(1, 2)) & (md != 0)
            return jnp.sum(eq.astype(jnp.int32))

        fns.append((len(idx), occ_fn("mldsa", fn)))

    for pset in sorted(getattr(ks._tables, "slhdsa_tables", {})):
        from ..tpu import slhdsa as tpuslh

        table = ks._tables.slhdsa_tables[pset]
        idx = _mldsa_alg_indices(pb, pb.status == 0, pset)
        if len(idx) == 0:
            continue
        rows = pb.kid_rows(idx, ks._kid_slhdsa_row[pset])
        if len(table.keys) == 1:
            rows = np.where(rows == -1, 0, rows)
        if (rows < 0).any():
            raise InvalidParameterError(
                f"{pset}: tokens with unknown kid")
        covered[idx] = True
        pad = _pad_size(len(idx), ks._max_chunk)
        if len(idx) > pad:
            raise InvalidParameterError("bucket exceeds max_chunk")
        sigs = [pb.signature(int(j)) for j in idx]
        msgs = [pb.signing_input(int(j)) for j in idx]
        if repeat > 1 or ks._mesh is not None:
            # The hypertree arrays are layer-major ([d, B, ...]) —
            # batch-axis tiling/sharding would hit the wrong axis.
            raise InvalidParameterError(
                f"{pset}: scaled/mesh resident mode is not supported "
                "for the SLH-DSA records")
        sprep = tpuslh._SLHPrep(table, sigs, msgs,
                                rows.astype(np.int32), pad)
        if not sprep.valid[: len(idx)].all():
            raise InvalidParameterError(
                f"{pset}: resident bench tokens must decode cleanly")
        # The verdict (hash-forest root compare) is computed entirely
        # on-device, so the accept-bit sum IS the integrity check —
        # same contract as the classical families.
        sdevs = [dev_put(a) for a in sprep.arrays()]

        def fn(sdevs=sdevs, table=table, tpuslh=tpuslh):
            ok = tpuslh._slh_jit()(table.pk_seed_l, table.pk_root_l,
                                   *sdevs)
            return jnp.sum(ok.astype(jnp.int32))

        fns.append((len(idx), occ_fn("slhdsa", fn)))

    if not covered.all():
        raise InvalidParameterError(
            "tokens outside the packed families: "
            f"{np.nonzero(~covered)[0][:5].tolist()}...")
    return int(covered.sum()), fns


def resident_slope_vps(n: int, fns, reps: int = 4,
                       trials: int = 3,
                       details: bool = False,
                       fns_scaled=None):
    """Slope-time resident dispatchers → verifies/sec, or None.

    THE resident methodology (bench.py ``resident_mixed_vps``,
    tools/profile_families.py — one implementation so a fix cannot
    diverge): each trial times a 1× run and a (1+``reps``)× run and
    takes the slope, cancelling dispatch/sync constants; the MINIMUM
    per-rep time across ``trials`` trials is the engine's (dispatch
    and the materializing sync ride the tunnel, so one stall shifts a
    single-trial slope by 2× — docs/PERF.md). Every run's accept-bit
    sum is checked against the token count, so a broken engine cannot
    produce a clean rate. Returns None when no trial yields a positive
    slope (timer noise on sub-millisecond families).

    ``fns_scaled``: dispatchers built with
    ``resident_dispatchers(..., repeat=1+reps)``. When given, the
    (1+reps)× run is ONE dispatch per family on (1+reps)×-tiled
    resident records instead of 1+reps dispatches — both slope points
    then issue the same dispatch count, so per-dispatch host/tunnel
    overhead (measured at 5-20 ms per program enqueue on the tunneled
    host — NOT engine time) cancels exactly instead of inflating the
    slope. Without it, the old dispatch-k-times behavior applies.

    ``details=True`` returns ``(vps_or_None, per_trial_vps)`` so
    callers can publish measurement spread alongside the estimate
    (VERDICT r4 #5: the point estimate alone hides stability). Note
    min-of-3 is over per-rep TIME, so in vps terms the estimate is
    the FASTEST trial: ``vps == max(per_trial_vps)``.
    """
    def run_multi(reps_: int) -> None:
        outs = []
        for _ in range(reps_):
            outs.extend(fn() for _, fn in fns)
        total = outs[0]
        for o in outs[1:]:
            total = total + o
        got = int(total)                  # materializing sync
        if got != reps_ * n:
            raise RuntimeError(
                f"resident engine verdict mismatch: {got} accepts "
                f"for {reps_}×{n} valid tokens")

    def run_scaled(reps_: int) -> None:
        use = fns if reps_ == 1 else fns_scaled
        outs = [fn() for _, fn in use]
        total = outs[0]
        for o in outs[1:]:
            total = total + o
        got = int(total)
        if got != reps_ * n:
            raise RuntimeError(
                f"resident engine verdict mismatch: {got} accepts "
                f"for {reps_}×{n} valid tokens")

    run = run_multi if fns_scaled is None else run_scaled
    run(1)                                # compile + settle
    run(1 + reps)
    per_trial = []
    for _ in range(trials):
        t0 = time.perf_counter()
        run(1)
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        run(1 + reps)
        tr = time.perf_counter() - t0
        per = (tr - t1) / reps
        if per > 0:
            per_trial.append(n / per)
    vps = max(per_trial) if per_trial else None
    if details:
        return vps, per_trial
    return vps


class _KeyTables(object):
    """One epoch's immutable key-table set: JWKs partitioned into
    per-family device tables plus the kid-routing maps.

    Everything a batch needs to resolve kids and dispatch lives here,
    built ONCE and never mutated — ``TPUBatchKeySet.swap_keys``
    installs a fresh instance atomically, so an in-flight batch that
    captured the previous instance finishes entirely on its epoch.
    """

    __slots__ = ("epoch", "jwks", "by_kid", "kids", "rsa_tables",
                 "n_rsa_keys", "ec_tables", "ed_table", "rsa_rows",
                 "ec_rows", "ed_rows", "kid_rsa_row", "kid_ec_row",
                 "kid_ed_row", "ec_keys", "ed_keys", "mldsa_keys",
                 "mldsa_rows", "mldsa_tables", "kid_mldsa_row",
                 "slhdsa_keys", "slhdsa_rows", "slhdsa_tables",
                 "kid_slhdsa_row")

    def __init__(self, jwks: Sequence[JWK], epoch: int = 0):
        # The OpenSSL-backed key types need the ``cryptography``
        # package; ML-DSA (AKP) keys and HostECPublicKey-backed EC
        # keys are dependency-free, so the partition duck-types those
        # FIRST and only isinstance-checks the crypto classes when
        # the package exists — an ML-DSA/host-EC keyset builds (and
        # hot-swaps) on crypto-less hosts.
        try:
            from cryptography.hazmat.primitives.asymmetric import (
                ec,
                ed25519,
                rsa,
            )
        except ImportError:
            ec = ed25519 = rsa = None

        self.epoch = int(epoch)
        self.jwks = list(jwks)
        # Partition keys into family tables; remember each JWK's slot.
        # RSA keys additionally split into SIZE CLASSES (one table per
        # limb width): a mixed 2048/4096 JWKS must not pad every
        # token's wire record to the widest key (the round-1 config-②
        # cliff). Rows encode as class*_RSA_CLS_STRIDE + row.
        from ..tpu.limbs import nlimbs_for_bits

        rsa_classes: List[list] = []      # per class: [(n, e), ...]
        rsa_class_need: List[int] = []    # per class: limb width
        self.rsa_rows: Dict[int, int] = {}
        self.ec_keys: Dict[str, list] = {}
        self.ec_rows: Dict[str, Dict[int, int]] = {}
        self.ed_keys, self.ed_rows = [], {}
        # Post-quantum: one table per parameter set (alg name = set
        # name), mirroring the per-curve EC layout. ML-DSA and
        # SLH-DSA keys both carry ``parameter_set``; the set name
        # routes the family.
        from ..tpu.slhdsa import PARAMS as _SLH_PARAMS

        self.mldsa_keys: Dict[str, list] = {}
        self.mldsa_rows: Dict[str, Dict[int, int]] = {}
        self.slhdsa_keys: Dict[str, list] = {}
        self.slhdsa_rows: Dict[str, Dict[int, int]] = {}
        for i, jwk in enumerate(self.jwks):
            key = jwk.key
            pset = getattr(key, "parameter_set", None)
            host_crv = getattr(key, "curve_name", None)
            if pset is not None and pset in _SLH_PARAMS:
                rows = self.slhdsa_rows.setdefault(pset, {})
                rows[i] = len(self.slhdsa_keys.setdefault(pset, []))
                self.slhdsa_keys[pset].append(key)
            elif pset is not None:               # MLDSAPublicKey
                rows = self.mldsa_rows.setdefault(pset, {})
                rows[i] = len(self.mldsa_keys.setdefault(pset, []))
                self.mldsa_keys[pset].append(key)
            elif host_crv is not None:           # HostECPublicKey
                rows = self.ec_rows.setdefault(host_crv, {})
                rows[i] = len(self.ec_keys.setdefault(host_crv, []))
                self.ec_keys[host_crv].append(key)
            elif rsa is not None and isinstance(key, rsa.RSAPublicKey):
                nums = key.public_numbers()
                need = nlimbs_for_bits(nums.n.bit_length())
                try:
                    cls = rsa_class_need.index(need)
                except ValueError:
                    cls = len(rsa_classes)
                    rsa_classes.append([])
                    rsa_class_need.append(need)
                self.rsa_rows[i] = (cls * _RSA_CLS_STRIDE
                                    + len(rsa_classes[cls]))
                rsa_classes[cls].append((nums.n, nums.e))
            elif ec is not None and isinstance(
                    key, ec.EllipticCurvePublicKey):
                crv = {"secp256r1": "P-256", "secp384r1": "P-384",
                       "secp521r1": "P-521"}[key.curve.name]
                rows = self.ec_rows.setdefault(crv, {})
                rows[i] = len(self.ec_keys.setdefault(crv, []))
                self.ec_keys[crv].append(key)
            elif ed25519 is not None and isinstance(
                    key, ed25519.Ed25519PublicKey):
                self.ed_rows[i] = len(self.ed_keys)
                self.ed_keys.append(key)

        self.rsa_tables: List[Any] = []
        if rsa_classes:
            from ..tpu.rsa import RSAKeyTable
            self.rsa_tables = [RSAKeyTable(nums) for nums in rsa_classes]
        self.n_rsa_keys = sum(len(c) for c in rsa_classes)
        self.ec_tables: Dict[str, Any] = {}
        for crv, keys in self.ec_keys.items():
            try:
                from ..tpu.ec import ECKeyTable
                self.ec_tables[crv] = ECKeyTable(crv, keys)
            except ImportError:
                pass  # EC engine not built yet → CPU fallback
        self.ed_table = None
        if self.ed_keys:
            try:
                from ..tpu.ed25519 import Ed25519KeyTable
                self.ed_table = Ed25519KeyTable(self.ed_keys)
            except ImportError:
                pass
        self.mldsa_tables: Dict[str, Any] = {}
        for pset, keys in self.mldsa_keys.items():
            try:
                from ..tpu.mldsa import MLDSAKeyTable
                self.mldsa_tables[pset] = MLDSAKeyTable(pset, keys)
            except ImportError:
                pass  # ML-DSA engine unavailable → CPU oracle
        self.slhdsa_tables: Dict[str, Any] = {}
        for pset, keys in self.slhdsa_keys.items():
            try:
                from ..tpu.slhdsa import SLHDSAKeyTable
                self.slhdsa_tables[pset] = SLHDSAKeyTable(pset, keys)
            except ImportError:
                pass  # SLH-DSA engine unavailable → CPU oracle

        self.by_kid: Dict[str, List[int]] = {}
        for i, jwk in enumerate(self.jwks):
            if jwk.kid:
                self.by_kid.setdefault(jwk.kid, []).append(i)
        self.kids = frozenset(self.by_kid)

        # kid → family table row, for kids resolving to exactly one key
        # (ambiguous kids take the trial-verify slow path)
        self.kid_rsa_row: Dict[str, int] = {}
        self.kid_ec_row: Dict[str, Dict[str, int]] = {c: {} for c in
                                                      self.ec_rows}
        self.kid_ed_row: Dict[str, int] = {}
        self.kid_mldsa_row: Dict[str, Dict[str, int]] = {
            p: {} for p in self.mldsa_rows}
        self.kid_slhdsa_row: Dict[str, Dict[str, int]] = {
            p: {} for p in self.slhdsa_rows}
        for kid, idxs in self.by_kid.items():
            if len(idxs) != 1:
                continue
            i = idxs[0]
            if i in self.rsa_rows:
                self.kid_rsa_row[kid] = self.rsa_rows[i]
            for crv, rows in self.ec_rows.items():
                if i in rows:
                    self.kid_ec_row[crv][kid] = rows[i]
            if i in self.ed_rows:
                self.kid_ed_row[kid] = self.ed_rows[i]
            for pset, rows in self.mldsa_rows.items():
                if i in rows:
                    self.kid_mldsa_row[pset][kid] = rows[i]
            for pset, rows in self.slhdsa_rows.items():
                if i in rows:
                    self.kid_slhdsa_row[pset][kid] = rows[i]


class TPUBatchKeySet(KeySet):
    """KeySet whose batch path runs on the TPU verify engine.

    Construct from JWKs (key + kid metadata). Single-token
    ``verify_signature`` uses the CPU oracle; ``verify_batch`` buckets
    and dispatches to the device.

    ``mesh``: an optional ``jax.sharding.Mesh`` — every packed chunk
    (RS*/ES*/EdDSA) then shards along the batch axis across the mesh's
    devices with replicated key tables (SURVEY.md §2.6 batch-DP +
    key-gather; validated on the virtual 8-device mesh by
    tests/test_parallel.py and the driver's dryrun_multichip).

    ``ec_ladder``: the ES* window-add law — ``"jacobian"``,
    ``"affine"``, or None for the global default
    (``cap_tpu.tpu.ec.ladder_mode``, env CAP_TPU_EC_LADDER). Verdicts
    are bit-exact either way; see docs/PERF.md for the A/B.

    ``epoch``: the key-material version this initial table set
    represents (the keyplane's counter); :meth:`swap_keys` installs
    later epochs without restarting anything — see docs/KEYPLANE.md.
    """

    def __init__(self, jwks: Sequence[JWK], max_chunk: int = 32768,
                 cpu_fallback: bool = True, mesh=None,
                 ec_ladder: Optional[str] = None, epoch: int = 0):
        if not jwks:
            raise NilParameterError("at least one key is required")
        if ec_ladder is not None:
            from ..tpu.ec import resolve_ladder

            resolve_ladder(ec_ladder)     # raises on unknown modes
        self._ec_ladder = ec_ladder
        self._max_chunk = max_chunk
        self._cpu_fallback = cpu_fallback
        self._mesh = mesh
        # Wire-adaptive chunk sizing (VERDICT r3 #3): EWMA of the
        # OBSERVED effective H2D byte rate, updated after every batch
        # collect; _chunk_tokens sizes chunks to a time budget against
        # it so a slow link gets smaller chunks (bounded p99) and a
        # fast link keeps big ones (throughput). None until the first
        # batch completes (the static 5 MB default applies).
        self._wire_bps: Optional[float] = None
        self._last_collect_t: Optional[float] = None
        self._chunk_budget_s = float(os.environ.get(
            "CAP_TPU_CHUNK_BUDGET_MS", "250")) / 1e3
        import threading

        self._swap_lock = threading.Lock()
        self._tables = _KeyTables(jwks, epoch=epoch)

    # -- epoch-versioned key tables (keyplane hot swap) --------------------

    @property
    def key_epoch(self) -> int:
        """Epoch of the tables NEW batches dispatch against."""
        return self._tables.epoch

    def swap_keys(self, jwks, epoch: Optional[int] = None,
                  grace_s: float = 30.0) -> int:
        """Hot-swap the key tables to a new epoch; returns the epoch.

        ``jwks``: a JWKS document (dict — parsed via
        :func:`cap_tpu.jwt.jwk.parse_jwks`) or a sequence of
        :class:`JWK`. ``epoch``: the keyplane's version for this
        material (default: current + 1).

        Semantics:

        - the replacement tables are built OFF the serving path (in
          the caller's thread — refresher/push threads, never a verify
          thread) and installed with one atomic reference swap;
        - batches already dispatched keep the table set they captured
          and finish entirely on their epoch;
        - for ``grace_s`` seconds, kids that exist only in the OLD
          epoch still resolve (the installed set is the new JWKS plus
          the retired-kid keys), so tokens signed moments before the
          rotation don't flap to unknown-kid rejects; after the grace
          window a pure new-epoch table set is built in the background
          and takes over.
        """
        if isinstance(jwks, dict):
            from .jwk import parse_jwks

            jwks = parse_jwks(jwks)
        jwks = list(jwks)
        if not jwks:
            raise NilParameterError("at least one key is required")
        import threading

        t0 = time.perf_counter()
        with self._swap_lock:
            old = self._tables
            new_epoch = old.epoch + 1 if epoch is None else int(epoch)
            new_kids = {j.kid for j in jwks if j.kid}
            retained = ([j for j in old.jwks
                         if j.kid and j.kid not in new_kids]
                        if grace_s > 0 else [])
            with telemetry.span(telemetry.SPAN_KEYPLANE_SWAP):
                self._tables = _KeyTables(jwks + retained,
                                          epoch=new_epoch)
        if retained:
            telemetry.count("keyplane.grace_kids", len(retained))
            timer = threading.Timer(
                grace_s, self._retire_grace, args=(jwks, new_epoch))
            timer.daemon = True
            timer.start()
        telemetry.count("keyplane.swaps")
        telemetry.observe("keyplane.swap_s", time.perf_counter() - t0)
        telemetry.gauge("keyplane.epoch", new_epoch)
        return new_epoch

    def _retire_grace(self, jwks, epoch: int) -> None:
        """Grace expiry: install the pure new-epoch tables (background
        thread — the build never runs on a verify path). A newer swap
        having landed meanwhile makes this a no-op."""
        try:
            pure = _KeyTables(jwks, epoch=epoch)
        except Exception:  # noqa: BLE001 - keep serving graced tables
            telemetry.count("keyplane.grace_retire_errors")
            return
        with self._swap_lock:
            if self._tables.epoch == epoch:
                self._tables = pure
                telemetry.count("keyplane.grace_retired")

    # Compatibility delegates: the pre-keyplane attribute names, used
    # by resident_dispatchers/bench/tests, read the CURRENT epoch.
    @property
    def _jwks(self):
        return self._tables.jwks

    @property
    def _by_kid(self):
        return self._tables.by_kid

    @property
    def _rsa_tables(self):
        return self._tables.rsa_tables

    @property
    def _n_rsa_keys(self):
        return self._tables.n_rsa_keys

    @property
    def _ec_tables(self):
        return self._tables.ec_tables

    @property
    def _ed_table(self):
        return self._tables.ed_table

    @property
    def _rsa_rows(self):
        return self._tables.rsa_rows

    @property
    def _ec_rows(self):
        return self._tables.ec_rows

    @property
    def _ed_rows(self):
        return self._tables.ed_rows

    @property
    def _kid_rsa_row(self):
        return self._tables.kid_rsa_row

    @property
    def _kid_ec_row(self):
        return self._tables.kid_ec_row

    @property
    def _kid_ed_row(self):
        return self._tables.kid_ed_row

    @property
    def _ec_keys(self):
        return self._tables.ec_keys

    @property
    def _ed_keys(self):
        return self._tables.ed_keys

    @property
    def _mldsa_tables(self):
        return self._tables.mldsa_tables

    @property
    def _kid_mldsa_row(self):
        return self._tables.kid_mldsa_row

    @property
    def _slhdsa_tables(self):
        return self._tables.slhdsa_tables

    @property
    def _kid_slhdsa_row(self):
        return self._tables.kid_slhdsa_row

    # -- single-token path (CPU oracle) -----------------------------------

    def _candidate_indices(self, parsed: ParsedJWS,
                           tables: Optional[_KeyTables] = None
                           ) -> List[int]:
        t = self._tables if tables is None else tables
        if parsed.kid is not None and parsed.kid in t.by_kid:
            pool = t.by_kid[parsed.kid]
        else:
            pool = range(len(t.jwks))
        return [i for i in pool
                if key_matches_alg(t.jwks[i].key, parsed.alg)]

    def verify_signature(self, token: str) -> Dict[str, Any]:
        return self._verify_parsed_trial(parse_jws(token))

    # -- batch path --------------------------------------------------------

    def _verify_parsed_trial(self, parsed: ParsedJWS,
                             tables: Optional[_KeyTables] = None):
        """Trial-verify one parsed token against the candidate keys —
        the single-token verdict logic, shared by verify_signature and
        the batch path's non-compactable JSON-form fallback."""
        t = self._tables if tables is None else tables
        last: Optional[Exception] = None
        for i in self._candidate_indices(parsed, t):
            try:
                verify_parsed(parsed, t.jwks[i].key)
                return parsed.claims()
            except InvalidSignatureError as e:
                last = e
        raise InvalidSignatureError(
            "no known key successfully validated the token signature"
        ) from last

    def verify_batch(self, tokens: Sequence[str]) -> List[Any]:
        from ..runtime import prep

        telemetry.count("verify_batch.calls")
        telemetry.count("verify_batch.tokens", len(tokens))
        with telemetry.span("verify_batch.total"):
            if prep._load_native() is not None:
                return self._collect_batch(self._dispatch_batch(tokens))
            # non-native prep parses every serialization itself
            return self._verify_batch_objects(tokens)

    def verify_batch_async(self, tokens: Sequence[str],
                           raw: bool = False):
        """Dispatch a batch; returns collect() → per-token results.

        All device work (transfers + programs) is queued before this
        returns; the returned thunk blocks on the one materializing
        sync. Dispatching the NEXT batch before collecting the previous
        one keeps the host↔device wire busy during host-side prep —
        the 2-deep pipelining the serve layer and bench use.

        ``raw``: accepted tokens yield payload BYTES instead of claims
        dicts (see verify_batch_async_raw).
        """
        from ..runtime import prep

        telemetry.count("verify_batch.calls")
        telemetry.count("verify_batch.tokens", len(tokens))
        if prep._load_native() is None:
            results = self._verify_batch_objects(tokens)
            if raw:
                from .jose import b64url_decode

                for i, r in enumerate(results):
                    if not isinstance(r, Exception):
                        # the dict was built from exactly these bytes
                        if is_json_form(tokens[i]):
                            results[i] = parse_jws(tokens[i]).payload
                        else:
                            results[i] = b64url_decode(
                                tokens[i].split(".")[1])
            return lambda: results
        state = self._dispatch_batch(tokens)
        if raw:
            state["raw"] = True
        return lambda: self._collect_batch(state)

    def verify_batch_raw(self, tokens: Sequence[str]) -> List[Any]:
        """Like verify_batch, but verified tokens yield their RAW
        payload bytes — the exact claims JSON the IdP signed."""
        with telemetry.span("verify_batch.total"):
            return self.verify_batch_async(tokens, raw=True)()

    def verify_batch_async_raw(self, tokens: Sequence[str]):
        """verify_batch_async returning payload BYTES for accepted
        tokens instead of parsed dicts.

        The serve path's zero-reserialization mode: the worker would
        otherwise build 64k claims dicts (tape phase 2) only to
        json.dumps them straight back onto the wire — the signed
        payload bytes ARE that JSON. Signature semantics are identical,
        including the claims()-path rejection of verified signatures
        over non-object payloads (phase-1 validation runs during the
        device drain as a fast filter; json.loads stays authoritative
        on the tokens it flags, so accept/reject decisions are
        byte-identical to the dict path's).
        """
        return self.verify_batch_async(tokens, raw=True)

    def verify_stream(self, batches, depth: int = 2):
        """Pipelined verification of an iterable of token batches.

        Yields each batch's results in order while keeping up to
        ``depth`` batches in flight: batch k+1's host prep + packing +
        H2D overlap batch k's device drain. The throughput shape the
        reference's sequential loop (jwt/keyset.go:126-139 per token)
        cannot express.
        """
        from collections import deque

        inflight: deque = deque()
        for tokens in batches:
            inflight.append(self.verify_batch_async(tokens))
            if len(inflight) >= depth:
                yield inflight.popleft()()
        while inflight:
            yield inflight.popleft()()

    def _dispatch_batch(self, tokens: Sequence[str]) -> dict:
        """Phase 1: prep, bucket, pack, and queue ALL device work."""
        from ..runtime.native_binding import ALG_NAMES, prepare_batch_arrays

        # Epoch capture: ONE immutable table set serves this whole
        # batch (dispatch, collect, slow-path trials) — a swap_keys
        # landing mid-batch changes only batches dispatched after it.
        tables = self._tables
        # Wire-estimate span starts HERE: transfers drain while later
        # chunks are still being packed, so measuring from dispatch END
        # would overestimate the link (the sync would block briefly on
        # an already-drained wire).
        t_dispatch = time.perf_counter()
        # Occupancy plane: the whole batch counts as ONE dispatch-level
        # busy interval spanning dispatch start → collect end (work in
        # flight); the per-family enqueue slices below are recorded
        # with dispatch=False so they feed lane-share accounting
        # without inflating device.dispatches or idle-gap records.
        occ_t0 = _occupancy.begin()
        from .jose import normalize_batch

        tokens, specials = normalize_batch(tokens)
        with telemetry.span("prep.native"):
            pb = prepare_batch_arrays(tokens)
        n = pb.n
        results: List[Any] = [None] * n
        ok = pb.status == 0
        for i in np.nonzero(~ok)[0]:
            results[int(i)] = pb.error(int(i))
        special_payloads: Dict[int, bytes] = {}
        for i, sp in specials.items():
            # normalization verdicts outrank the ""-sentinel's prep
            # error: the exact parse exception, or (non-compactable
            # JSON form) the single-token trial verdict.
            if isinstance(sp, Exception):
                results[i] = sp
            else:
                try:
                    results[i] = self._verify_parsed_trial(sp, tables)
                    special_payloads[i] = sp.payload
                except Exception as e:  # noqa: BLE001 - per-token
                    results[i] = e

        slow: List[int] = []
        # Two-phase device interaction: every bucket's device work is
        # DISPATCHED here (transfers are asynchronous on the JAX
        # runtime — they queue on the wire and overlap the packing of
        # later chunks and the next batch's prep), then _collect_batch
        # materializes ONE concatenated verdict array. Hot families
        # (RS*, ES*) go through the PACKED path: one u8 record transfer
        # + one compiled program per chunk. Compute-heavy families
        # dispatch first so their device time overlaps the later
        # families' H2D transfers (docs/PERF.md).
        pending: List[tuple] = []
        packed_parts: List[Any] = []      # device [pad] bool arrays
        packed_meta: List[tuple] = []     # (n_slots, consume(arrs))
        stats = {"h2d": 0}                # record bytes this batch
        alg_ids = {name: i for i, name in enumerate(ALG_NAMES)}

        def run_family(alg_name: str, runner) -> None:
            idx = np.nonzero(ok & (pb.alg_id == alg_ids[alg_name]))[0]
            if len(idx) == 0:
                return
            runner(alg_name, idx)

        def run_rs(alg_name: str, idx: np.ndarray) -> None:
            with _occupancy.interval("rsa", dispatch=False):
                self._run_rsa_packed("rs", _RS[alg_name], idx, pb,
                                     packed_parts, packed_meta, pending,
                                     slow, results, stats, tables)

        def run_ps(alg_name: str, idx: np.ndarray) -> None:
            # Every PS* family rides the packed single-transfer path
            # with the device-side EMSA-PSS check (SHA-256 via
            # tpu/sha256.py, SHA-384/512 via the u32-pair engine in
            # tpu/sha512.py) — no EM bytes return to the host.
            with _occupancy.interval("rsa", dispatch=False):
                self._run_rsa_packed("ps", _PS[alg_name], idx, pb,
                                     packed_parts, packed_meta,
                                     pending, slow, results, stats,
                                     tables)

        def run_es(alg_name: str, idx: np.ndarray) -> None:
            with _occupancy.interval("ec", dispatch=False):
                self._run_ec_packed(alg_name, idx, pb, packed_parts,
                                    packed_meta, pending, slow, results,
                                    stats, tables)

        def run_ed(alg_name: str, idx: np.ndarray) -> None:
            with _occupancy.interval("ed", dispatch=False):
                self._run_ed_packed(idx, pb, packed_parts, packed_meta,
                                    pending, slow, results, stats,
                                    tables)

        # Post-quantum first: the deepest device programs (the
        # SLH-DSA hash forest, then the ML-DSA NTT network) go on the
        # wire before the cheaper families, so their device time
        # overlaps the later families' packing + transfers.
        for pset in sorted(tables.slhdsa_tables):
            idx = _mldsa_alg_indices(pb, ok, pset)
            if len(idx):
                with _occupancy.interval("slhdsa", dispatch=False):
                    self._run_slhdsa_packed(pset, idx, pb, pending,
                                            slow, stats, tables)
        for pset in sorted(tables.mldsa_tables):
            idx = _mldsa_alg_indices(pb, ok, pset)
            if len(idx):
                with _occupancy.interval("mldsa", dispatch=False):
                    self._run_mldsa_packed(pset, idx, pb, pending,
                                           slow, stats, tables)
        for a, crv in _ES.items():
            if crv in tables.ec_tables:
                run_family(a, run_es)
        if tables.ed_table is not None:
            run_family(algs.EdDSA, run_ed)
        if tables.rsa_tables:
            for a in _RS:
                run_family(a, run_rs)
            for a in _PS:
                run_family(a, run_ps)

        return dict(pb=pb, n=n, ok=ok, results=results, slow=slow,
                    pending=pending, packed_parts=packed_parts,
                    packed_meta=packed_meta, stats=stats,
                    t_dispatch=t_dispatch, occ_t0=occ_t0, tables=tables,
                    special_payloads=special_payloads)

    def _collect_batch(self, state: dict) -> List[Any]:
        """Phase 2: claims prefetch, materializing sync, verdicts."""
        pb, n, ok = state["pb"], state["n"], state["ok"]
        results, slow = state["results"], state["slow"]
        pending = state["pending"]
        packed_parts = state["packed_parts"]
        packed_meta = state["packed_meta"]

        raw = state.get("raw", False)
        with telemetry.span("device.sync"):
            if raw:
                # Raw mode replaces dict building with the phase-1-only
                # object check; the mask drives _finish_arrays for the
                # packed AND arrays paths, overlapping the drain.
                with telemetry.span("claims.validate"):
                    idxs = np.nonzero(ok)[0]
                    mask = np.zeros(n, bool)
                    mask[idxs] = pb.payload_object_ok(idxs)
                    pb._raw_ok = mask
            if packed_parts:
                import jax.numpy as jnp

                flat_dev = (jnp.concatenate(packed_parts)
                            if len(packed_parts) > 1 else packed_parts[0])
                # Overlap the host-side claims parsing with the device
                # drain (transfers + compute are still in flight; only
                # np.asarray below truly blocks). Every ok-status token
                # still has results[i] None here (only prep errors are
                # filled), so the index set is just the ok mask.
                if not raw:
                    with telemetry.span("claims.prefetch"):
                        pb.prefetch_claims(np.nonzero(ok)[0])
                flat = np.asarray(flat_dev)
                off = 0
                for n_slots, consume in packed_meta:
                    arrs = []
                    for sz in n_slots:
                        arrs.append(flat[off:off + sz])
                        off += sz
                    consume(arrs)
            for chunk, m, fin in pending:
                self._finish_arrays(chunk, fin()[:m], pb, results)

        # families without device tables (or EC/Ed engines not built):
        slow_set = set(slow)
        for j in range(n):
            if ok[j] and results[j] is None and j not in slow_set:
                slow_set.add(j)

        if slow_set:
            telemetry.count("cpu_fallback.tokens", len(slow_set))
            with telemetry.span("cpu_fallback"):
                for j in sorted(slow_set):
                    out = self._verify_one_parsed(pb.parsed(j),
                                                  state.get("tables"))
                    if raw and not isinstance(out, Exception):
                        # the oracle built the dict from these bytes
                        out = pb.payload_bytes(j)
                    results[j] = out
        if raw:
            # non-compactable JSON-form tokens verified on the object
            # path during dispatch: same raw contract, their bytes.
            for i, pay in state.get("special_payloads", {}).items():
                if not isinstance(results[i], Exception):
                    results[i] = pay
        self._observe_wire(state)
        # Device-surface decision records: families come straight from
        # the prep arrays (no token re-parsing on the hot path).
        if telemetry.active() is not None:
            from ..runtime.native_binding import ALG_NAMES

            fam_for = [_decision.family_for_alg(a) for a in ALG_NAMES]
            alg_id = pb.alg_id

            def fam(j: int) -> str:
                if not ok[j]:
                    return "unknown"
                aid = int(alg_id[j])
                if aid >= 0:
                    return fam_for[aid]
                # non-interned algs (ML-DSA et al.) carry raw bytes
                return _decision.family_for_alg(pb.alg(j))

            fams = [fam(j) for j in range(n)]
            t_dispatch = state.get("t_dispatch")
            _decision.record_batch(
                "tpu", results, families=fams,
                latency_s=(time.perf_counter() - t_dispatch
                           if t_dispatch is not None else None))
        # Close the batch's dispatch-level busy interval: dispatch
        # start → collect end is the window this batch held device
        # work in flight (the occupancy numerator).
        _occupancy.end("flight", state.get("occ_t0"))
        return results

    def _observe_wire(self, state: dict) -> None:
        """Update the observed effective H2D rate after one batch.

        Two candidate estimates, take the MAX:
        - bytes / (now - previous collect end): the bench's
          steady-state definition — right under pipelined load but
          poisoned by idle gaps between batches;
        - bytes / (now - this batch's dispatch start): spans up to
          ``depth`` intervals under pipelining (≈2× low) but contains
          no idle time.
        Under load the interval estimate wins; when idle the span
        estimate wins — so the EWMA never collapses from a quiet
        period and chunks don't shrink to the floor for no reason.
        """
        now = time.perf_counter()
        h2d = state.get("stats", {}).get("h2d", 0)
        t_dispatch = state.get("t_dispatch")
        last, self._last_collect_t = self._last_collect_t, now
        if not h2d or t_dispatch is None:
            return
        span = now - t_dispatch
        est = h2d / span if span > 0 else 0.0
        if last is not None and now > last:
            est = max(est, h2d / (now - last))
        if est <= 0:
            return
        prev = self._wire_bps
        self._wire_bps = est if prev is None else 0.5 * prev + 0.5 * est
        telemetry.observe("wire.est_mbps", self._wire_bps / (1 << 20))

    @staticmethod
    def _finish_arrays(chunk, okv, pb, results: List[Any]) -> None:
        """Write per-token verdicts for one array-path device chunk.

        Raw mode (``pb._raw_ok`` set by _collect_batch): accepted
        tokens yield their payload BYTES; a verified signature over a
        non-object payload raises through claims() so the error object
        is byte-identical to the dict path's.
        """
        raw_ok = getattr(pb, "_raw_ok", None)
        cache = getattr(pb, "_claims_cache", None)
        if cache is None:
            cache = {}
        claims = pb.claims
        msg = ("no known key successfully validated the token "
               "signature")
        for j, good in zip(np.asarray(chunk).tolist(),
                           np.asarray(okv).tolist()):
            if good:
                if raw_ok is not None:
                    if raw_ok[j]:
                        results[j] = pb.payload_bytes(j)
                    else:
                        # The phase-1 mask is only a FAST FILTER:
                        # json.loads stays authoritative (it accepts
                        # e.g. BOM-prefixed payloads the strict scan
                        # flags), exactly like the dict path.
                        try:
                            claims(j)
                            results[j] = pb.payload_bytes(j)
                        except MalformedTokenError as e:
                            results[j] = e
                    continue
                hit = cache.get(j)
                if hit is None:
                    try:
                        hit = claims(j)
                    except MalformedTokenError as e:
                        hit = e
                results[j] = hit
            else:
                results[j] = InvalidSignatureError(msg)

    def _chunk_tokens(self, rec_width: int) -> int:
        """Tokens per packed chunk, pow-2 for shape reuse.

        Until the first batch completes: target ~5 MB transfers (the
        tunnel's bandwidth sweet spot, tools/probe_tunnel.py). After:
        target the TIME budget (CAP_TPU_CHUNK_BUDGET_MS, default 250)
        against the observed effective H2D rate, clamped to [1, 8] MB —
        a 6 MB/s trough then gets ~1.5 MB chunks (bounded per-chunk
        latency, finer pipeline overlap) while a fast link keeps large
        ones (VERDICT r3 #3)."""
        budget_bytes = 5 << 20
        bps = self._wire_bps
        if bps:
            budget_bytes = min(max(int(bps * self._chunk_budget_s),
                                   1 << 20), 8 << 20)
        c = 1024
        while c * 2 * rec_width <= budget_bytes:
            c *= 2
        return min(self._max_chunk, max(1024, c))

    def _run_rsa_packed(self, kind: str, hash_name: str,
                        idx: np.ndarray, pb,
                        packed_parts: List[Any],
                        packed_meta: List[tuple],
                        pending: List[tuple],
                        slow: List[int], results: List[Any],
                        stats: dict,
                        tables: Optional[_KeyTables] = None) -> None:
        from ..tpu import rsa as tpursa

        t = self._tables if tables is None else tables
        rows = pb.kid_rows(idx, t.kid_rsa_row)
        if t.n_rsa_keys == 1:
            rows = np.where(rows == -1, 0, rows)
        fast = rows >= 0
        slow.extend(int(i) for i in idx[~fast])
        idx = idx[fast]
        rows = rows[fast].astype(np.int32)
        if len(idx) == 0:
            return
        h_len = tpursa.HASH_LEN[hash_name]
        for cls, table in enumerate(t.rsa_tables):
            sel = (rows // _RSA_CLS_STRIDE) == cls
            if not sel.any():
                continue
            cls_idx = idx[sel]
            cls_rows = rows[sel] % _RSA_CLS_STRIDE
            if len(table.n_ints) > 255:    # kid row must fit a u8
                self._run_rsa_arrays(kind, hash_name, cls_idx, pb,
                                     pending, slow, stats, cls=cls,
                                     tables=t)
                continue
            width = 2 * table.k
            chunk_n = self._chunk_tokens(width + h_len
                                         + tpursa.RS_REC_EXTRA)
            for lo in range(0, len(cls_idx), chunk_n):
                chunk = cls_idx[lo: lo + chunk_n]
                crows = cls_rows[lo: lo + chunk_n]
                m = len(chunk)
                pad = _pad_size(m, chunk_n)
                telemetry.count(f"device.{kind}.tokens", m)
                _pad_telemetry(kind, m, pad)
                with telemetry.span(f"dispatch.{kind}.{hash_name}"):
                    rec = _pack_rsa_record(pb, table, kind, hash_name,
                                           chunk, crows, pad)
                    telemetry.count("h2d.bytes", rec.nbytes)
                    stats["h2d"] += rec.nbytes
                    if kind == "rs":
                        ok_dev = tpursa.verify_rs_packed_pending(
                            table, rec, hash_name, mesh=self._mesh)
                    else:
                        ok_dev = tpursa.verify_ps_packed_pending(
                            table, rec, hash_name, mesh=self._mesh)
                packed_parts.append(ok_dev)

                def consume(arrs, chunk=chunk, m=m):
                    self._finish_arrays(chunk, arrs[0][:m], pb, results)

                packed_meta.append(([pad], consume))

    def _run_ec_packed(self, alg: str, idx: np.ndarray, pb,
                       packed_parts: List[Any],
                       packed_meta: List[tuple],
                       pending: List[tuple],
                       slow: List[int], results: List[Any],
                       stats: dict,
                       tables: Optional[_KeyTables] = None) -> None:
        from ..tpu import ec as tpuec
        from ..tpu.rsa import HASH_LEN

        t = self._tables if tables is None else tables
        crv = _ES[alg]
        table = t.ec_tables[crv]
        if len(table.keys) > 255:
            return self._run_ec_arrays(alg, idx, pb, pending, slow,
                                       stats, tables=t)
        hash_len = HASH_LEN[algs.HASH_FOR_ALG[alg]]
        rows = pb.kid_rows(idx, t.kid_ec_row[crv])
        if len(table.keys) == 1:
            rows = np.where(rows == -1, 0, rows)
        fast = rows >= 0
        slow.extend(int(i) for i in idx[~fast])
        idx = idx[fast]
        rows = rows[fast].astype(np.int32)
        if len(idx) == 0:
            return
        cb = table.curve.coord_bytes
        width = 2 * cb
        chunk_n = self._chunk_tokens(width + hash_len + tpuec.ES_REC_EXTRA)
        for lo in range(0, len(idx), chunk_n):
            chunk = idx[lo: lo + chunk_n]
            crows = rows[lo: lo + chunk_n]
            m = len(chunk)
            pad = _pad_size(m, chunk_n)
            telemetry.count("device.es.tokens", m)
            _pad_telemetry("es", m, pad)
            with telemetry.span(f"dispatch.es.{crv}"):
                rec = _pack_es_record(pb, table, chunk, crows,
                                      hash_len, pad)
                telemetry.count("h2d.bytes", rec.nbytes)
                stats["h2d"] += rec.nbytes
                ok_dev, deg_dev = tpuec.verify_es_packed_pending(
                    table, rec, hash_len, mesh=self._mesh,
                    ladder=self._ec_ladder)
            packed_parts.append(ok_dev)
            packed_parts.append(deg_dev)

            def consume(arrs, chunk=chunk, m=m, rec=rec, crows=crows,
                        table=table, cb=cb, hash_len=hash_len):
                okv = np.array(arrs[0][:m])
                deg = arrs[1][:m]
                for j in np.nonzero(deg)[0]:
                    okv[j] = tpuec._cpu_verify_one(
                        table, int(crows[j]),
                        rec[j, : 2 * cb].tobytes(),
                        rec[j, 2 * cb: 2 * cb + hash_len].tobytes())
                self._finish_arrays(chunk, okv, pb, results)

            packed_meta.append(([pad, pad], consume))

    def _run_mldsa_packed(self, pset: str, idx: np.ndarray, pb,
                          pending: List[tuple],
                          slow: List[int], stats: dict,
                          tables: Optional[_KeyTables] = None) -> None:
        """One ML-DSA parameter set through the two-phase device path.

        Default (``mldsa.fused_enabled()``): the FUSED single-round-
        trip engine — host work per token is byte decode ONLY (length/
        range/hint gates, lane packing); μ, SampleInBall, the NTT
        network, w1Encode, and the c̃ compare all run in one device
        dispatch (batched Keccak lanes), and the verdict closure just
        materializes bits. Zero per-token host SHAKE — span/counter-
        pinned by tests/test_mldsa_fused.py. With the fused path off
        (CAP_TPU_MLDSA_FUSED=0) the r11 two-phase split applies: host
        μ/c̃ hashing around the device NTT. Tokens whose kid cannot be
        routed fall to the CPU oracle — which for ML-DSA is the same
        pure-int ``py_verify`` math, so verdict parity is structural.
        """
        from ..tpu import mldsa as tpumldsa

        t = self._tables if tables is None else tables
        table = t.mldsa_tables[pset]
        p = table.params
        rows = pb.kid_rows(idx, t.kid_mldsa_row[pset])
        if len(table.keys) == 1:
            rows = np.where(rows == -1, 0, rows)
        fast = rows >= 0
        slow.extend(int(i) for i in idx[~fast])
        idx = idx[fast]
        rows = rows[fast].astype(np.int32)
        if len(idx) == 0:
            return
        # Per-token device bytes: z lanes (l·256 u32) + c lanes
        # (256 u32) + hint lanes (k·256 u8) + the key row.
        bpt = (p.l + 1) * N_COEFF * 4 + p.k * N_COEFF + 4
        chunk_n = self._chunk_tokens(max(1, bpt // 2))
        for lo in range(0, len(idx), chunk_n):
            chunk = idx[lo: lo + chunk_n]
            crows = rows[lo: lo + chunk_n]
            m = len(chunk)
            pad = _pad_size(m, chunk_n)
            sigs = [pb.signature(int(j)) for j in chunk]
            msgs = [pb.signing_input(int(j)) for j in chunk]
            telemetry.count("device.mldsa.tokens", m)
            _pad_telemetry("mldsa", m, pad)
            h2d = pad * bpt
            telemetry.count("h2d.bytes", h2d)
            stats["h2d"] += h2d
            with telemetry.span(f"dispatch.mldsa.{pset}"):
                verify = (tpumldsa.verify_mldsa_fused_pending
                          if tpumldsa.fused_enabled()
                          else tpumldsa.verify_mldsa_pending)
                fin = verify(table, sigs, msgs, crows, pad=pad,
                             mesh=self._mesh)
            pending.append((chunk, m, fin))

    def _run_slhdsa_packed(self, pset: str, idx: np.ndarray, pb,
                           pending: List[tuple],
                           slow: List[int], stats: dict,
                           tables: Optional[_KeyTables] = None) -> None:
        """One SLH-DSA parameter set through the two-phase device
        path: host decode (sig split + the single H_msg SHAKE + ADRS
        lane precompute) at dispatch, the whole FORS/hypertree hash
        forest queued on the device, verdict bits at the batch-wide
        sync. Unroutable kids fall to the CPU oracle — the same
        hashlib math, so verdict parity is structural."""
        from ..tpu import slhdsa as tpuslh

        t = self._tables if tables is None else tables
        table = t.slhdsa_tables[pset]
        p = table.params
        rows = pb.kid_rows(idx, t.kid_slhdsa_row[pset])
        if len(table.keys) == 1:
            rows = np.where(rows == -1, 0, rows)
        fast = rows >= 0
        slow.extend(int(i) for i in idx[~fast])
        idx = idx[fast]
        rows = rows[fast].astype(np.int32)
        if len(idx) == 0:
            return
        # Per-token device bytes ≈ the signature's hash values plus
        # ~500 precomputed 32-byte ADRS words as interleaved lanes.
        bpt = p.sig_size + 32 * (p.k * (p.a + 1) + 1
                                 + p.d * (p.wlen + p.hp + 1))
        chunk_n = self._chunk_tokens(max(1, bpt // 2))
        for lo in range(0, len(idx), chunk_n):
            chunk = idx[lo: lo + chunk_n]
            crows = rows[lo: lo + chunk_n]
            m = len(chunk)
            # Pow-2 padding with a 16-row floor instead of the global
            # _MIN_BUCKET: one SLH-DSA lane-row is ~300x the device
            # work of a classical record, so at small batches the
            # fill-ratio waste dominates what recompile amortization
            # saves (device.slhdsa.fill_ratio tells the story).
            pad = 16
            while pad < m:
                pad *= 2
            pad = min(pad, chunk_n)
            sigs = [pb.signature(int(j)) for j in chunk]
            msgs = [pb.signing_input(int(j)) for j in chunk]
            telemetry.count("device.slhdsa.tokens", m)
            _pad_telemetry("slhdsa", m, pad)
            h2d = pad * bpt
            telemetry.count("h2d.bytes", h2d)
            stats["h2d"] += h2d
            with telemetry.span(f"dispatch.slhdsa.{pset}"):
                fin = tpuslh.verify_slhdsa_pending(
                    table, sigs, msgs, crows, pad=pad, mesh=self._mesh)
            pending.append((chunk, m, fin))

    def _run_rsa_arrays(self, kind: str, hash_name: str, idx: np.ndarray,
                        pb, pending: List[tuple],
                        slow: List[int], stats: dict,
                        cls: Optional[int] = None,
                        tables: Optional[_KeyTables] = None) -> None:
        from ..tpu import rsa as tpursa

        t = self._tables if tables is None else tables
        rows = pb.kid_rows(idx, t.kid_rsa_row)
        if t.n_rsa_keys == 1:
            # single-key family: kid-less tokens have exactly one
            # candidate — dispatch them to the device (row 0), matching
            # the object path's single-candidate routing
            rows = np.where(rows == -1, 0, rows)
        fast = rows >= 0
        slow.extend(int(i) for i in idx[~fast])
        idx = idx[fast]
        rows = rows[fast].astype(np.int32)
        if len(idx) == 0:
            return
        for c, table in enumerate(t.rsa_tables):
            if cls is not None and c != cls:
                continue
            sel = (rows // _RSA_CLS_STRIDE) == c
            if not sel.any():
                continue
            cls_idx = idx[sel]
            cls_rows = rows[sel] % _RSA_CLS_STRIDE
            width = 2 * table.k
            for lo in range(0, len(cls_idx), self._max_chunk):
                chunk = cls_idx[lo: lo + self._max_chunk]
                crows = cls_rows[lo: lo + self._max_chunk]
                m = len(chunk)
                pad = _pad_size(m, self._max_chunk)
                sig_mat = np.zeros((pad, width), np.uint8)
                sig_mat[:m] = pb.sig_matrix(chunk, width)
                sig_lens = np.zeros(pad, np.int64)
                sig_lens[:m] = pb.sig_len[chunk]
                hash_mat = np.zeros((pad, 64), np.uint8)
                hash_mat[:m] = pb.digest[chunk]
                key_idx = np.zeros(pad, np.int32)
                key_idx[:m] = crows
                telemetry.count(f"device.{kind}.tokens", m)
                _pad_telemetry(kind, m, pad)
                h2d = (sig_mat.nbytes + sig_lens.nbytes
                       + hash_mat.nbytes + key_idx.nbytes)
                telemetry.count("h2d.bytes", h2d)
                stats["h2d"] += h2d
                with telemetry.span(f"dispatch.{kind}.{hash_name}"):
                    if kind == "rs":
                        fin = tpursa.verify_pkcs1v15_arrays_pending(
                            table, sig_mat, sig_lens, hash_mat,
                            hash_name, key_idx)
                    else:
                        fin = tpursa.verify_pss_arrays_pending(
                            table, sig_mat, sig_lens, hash_mat,
                            hash_name, key_idx)
                pending.append((chunk, m, fin))

    def _run_ec_arrays(self, alg: str, idx: np.ndarray, pb,
                       pending: List[tuple], slow: List[int],
                       stats: dict,
                       tables: Optional[_KeyTables] = None) -> None:
        from ..tpu import ec as tpuec
        from ..tpu.rsa import HASH_LEN

        t = self._tables if tables is None else tables
        crv = _ES[alg]
        table = t.ec_tables[crv]
        hash_len = HASH_LEN[algs.HASH_FOR_ALG[alg]]
        rows = pb.kid_rows(idx, t.kid_ec_row[crv])
        if len(table.keys) == 1:
            # kid-less tokens have exactly one candidate on this curve
            rows = np.where(rows == -1, 0, rows)
        fast = rows >= 0
        slow.extend(int(i) for i in idx[~fast])
        idx = idx[fast]
        rows = rows[fast].astype(np.int32)
        if len(idx) == 0:
            return
        width = 2 * table.coord_bytes
        for lo in range(0, len(idx), self._max_chunk):
            chunk = idx[lo: lo + self._max_chunk]
            crows = rows[lo: lo + self._max_chunk]
            m = len(chunk)
            pad = _pad_size(m, self._max_chunk)
            sig_mat = np.zeros((pad, width), np.uint8)
            sig_mat[:m] = pb.sig_matrix(chunk, width)
            sig_lens = np.zeros(pad, np.int64)
            sig_lens[:m] = pb.sig_len[chunk]
            hash_mat = np.zeros((pad, 64), np.uint8)
            hash_mat[:m] = pb.digest[chunk]
            key_idx = np.zeros(pad, np.int32)
            key_idx[:m] = crows
            telemetry.count("device.es.tokens", m)
            _pad_telemetry("es", m, pad)
            h2d = (sig_mat.nbytes + sig_lens.nbytes + hash_mat.nbytes
                   + key_idx.nbytes)
            telemetry.count("h2d.bytes", h2d)
            stats["h2d"] += h2d
            with telemetry.span(f"dispatch.es.{crv}"):
                fin = tpuec.verify_ecdsa_arrays_pending(
                    table, sig_mat, sig_lens, hash_mat, hash_len,
                    key_idx, ladder=self._ec_ladder)
            pending.append((chunk, m, fin))

    def _run_ed_packed(self, idx: np.ndarray, pb,
                       packed_parts: List[Any],
                       packed_meta: List[tuple],
                       pending: List[tuple],
                       slow: List[int], results: List[Any],
                       stats: dict,
                       tables: Optional[_KeyTables] = None) -> None:
        from ..tpu import ed25519 as tpued

        t = self._tables if tables is None else tables
        table = t.ed_table
        if len(table.keys) > 255:
            return self._run_ed_arrays(idx, pb, pending, slow, stats,
                                       tables=t)
        rows = pb.kid_rows(idx, t.kid_ed_row)
        if len(table.keys) == 1:
            rows = np.where(rows == -1, 0, rows)
        fast = rows >= 0
        slow.extend(int(i) for i in idx[~fast])
        idx = idx[fast]
        rows = rows[fast].astype(np.int32)
        if len(idx) == 0:
            return
        chunk_n = self._chunk_tokens(64 + 32 + tpued.ED_REC_EXTRA)
        for lo in range(0, len(idx), chunk_n):
            chunk = idx[lo: lo + chunk_n]
            crows = rows[lo: lo + chunk_n]
            m = len(chunk)
            pad = _pad_size(m, chunk_n)
            sigs = [pb.signature(int(j)) for j in chunk]
            msgs = [pb.signing_input(int(j)) for j in chunk]
            fill = pad - m
            sigs += [b""] * fill
            msgs += [b""] * fill
            key_idx = np.concatenate([crows, np.zeros(fill, np.int32)])
            telemetry.count("device.ed.tokens", m)
            _pad_telemetry("ed", m, pad)
            with telemetry.span("dispatch.ed25519"):
                rec = tpued.ed_packed_records(table, sigs, msgs, key_idx)
                telemetry.count("h2d.bytes", rec.nbytes)
                stats["h2d"] += rec.nbytes
                ok_dev = tpued.verify_ed_packed_pending(
                    table, rec, mesh=self._mesh)
            packed_parts.append(ok_dev)

            def consume(arrs, chunk=chunk, m=m):
                self._finish_arrays(chunk, arrs[0][:m], pb, results)

            packed_meta.append(([pad], consume))

    def _run_ed_arrays(self, idx: np.ndarray, pb,
                       pending: List[tuple], slow: List[int],
                       stats: dict,
                       tables: Optional[_KeyTables] = None) -> None:
        from ..tpu import ed25519 as tpued

        t = self._tables if tables is None else tables
        table = t.ed_table
        rows = pb.kid_rows(idx, t.kid_ed_row)
        if len(table.keys) == 1:
            # kid-less tokens have exactly one EdDSA candidate
            rows = np.where(rows == -1, 0, rows)
        fast = rows >= 0
        slow.extend(int(i) for i in idx[~fast])
        idx = idx[fast]
        rows = rows[fast].astype(np.int32)
        if len(idx) == 0:
            return
        for lo in range(0, len(idx), self._max_chunk):
            chunk = idx[lo: lo + self._max_chunk]
            crows = rows[lo: lo + self._max_chunk]
            m = len(chunk)
            pad = _pad_size(m, self._max_chunk)
            sigs = [pb.signature(int(j)) for j in chunk]
            msgs = [pb.signing_input(int(j)) for j in chunk]
            fill = pad - m
            sigs += [b"\x00" * 64] * fill
            msgs += [b""] * fill
            key_idx = np.concatenate([crows, np.zeros(fill, np.int32)])
            telemetry.count("device.ed.tokens", m)
            _pad_telemetry("ed", m, pad)
            h2d = (sum(len(x) for x in sigs)
                   + sum(len(x) for x in msgs) + key_idx.nbytes)
            telemetry.count("h2d.bytes", h2d)
            stats["h2d"] += h2d
            with telemetry.span("dispatch.ed25519"):
                fin = tpued.verify_ed25519_batch_pending(
                    table, sigs, msgs, key_idx)
            pending.append((chunk, m, fin))

    def _verify_one_parsed(self, p,
                           tables: Optional[_KeyTables] = None) -> Any:
        """CPU trial verification of one parsed token (slow path)."""
        t = self._tables if tables is None else tables
        if not self._cpu_fallback:
            return InvalidParameterError(
                "token cannot be dispatched to the device engine and "
                "CPU fallback is disabled")
        last: Optional[Exception] = None
        for i in self._candidate_indices(p, t):
            try:
                verify_parsed(p, t.jwks[i].key)
                try:
                    return p.claims()
                except MalformedTokenError as e:
                    return e
            except InvalidSignatureError as e:
                last = e
        err = InvalidSignatureError(
            "no known key successfully validated the token signature")
        err.__cause__ = last
        return err

    def _verify_batch_objects(self, tokens: Sequence[str]) -> List[Any]:
        n = len(tokens)
        tables = self._tables        # one epoch serves this batch
        results: List[Any] = [None] * n
        parsed_list: List[Optional[ParsedJWS]] = [None] * n
        key_for: List[Optional[int]] = [None] * n

        from ..runtime import prep  # C++ when built, Python fallback

        with telemetry.span("prep"):
            prepped = prep.prepare_batch(tokens)

        for j, p in enumerate(prepped):
            if isinstance(p, Exception):
                results[j] = p
                continue
            parsed_list[j] = p
            cands = self._candidate_indices(p, tables)
            if len(cands) == 1:
                key_for[j] = cands[0]
            elif not cands:
                results[j] = InvalidSignatureError(
                    "no known key successfully validated the token signature"
                )
            # >1 candidate (ambiguous kid / no kid): CPU trial path below.

        buckets: Dict[tuple, List[int]] = {}
        for j, p in enumerate(parsed_list):
            if results[j] is not None or p is None:
                continue
            if key_for[j] is None:
                buckets.setdefault(("cpu",), []).append(j)
            elif p.alg in _RS and tables.rsa_tables:
                buckets.setdefault(("rs", _RS[p.alg]), []).append(j)
            elif p.alg in _PS and tables.rsa_tables:
                buckets.setdefault(("ps", _PS[p.alg]), []).append(j)
            elif p.alg in _ES and _ES[p.alg] in tables.ec_tables:
                buckets.setdefault(("es", p.alg), []).append(j)
            elif p.alg == algs.EdDSA and tables.ed_table is not None:
                buckets.setdefault(("ed",), []).append(j)
            elif p.alg in tables.mldsa_tables:
                buckets.setdefault(("mldsa", p.alg), []).append(j)
            elif p.alg in tables.slhdsa_tables:
                buckets.setdefault(("slhdsa", p.alg), []).append(j)
            else:
                buckets.setdefault(("cpu",), []).append(j)

        for kind, idxs in buckets.items():
            if kind[0] == "cpu":
                self._run_cpu(idxs, parsed_list, results, tables)
            elif kind[0] in ("rs", "ps"):
                self._run_rsa(kind[0], kind[1], idxs, parsed_list,
                              key_for, results, tables)
            elif kind[0] == "es":
                self._run_ec(kind[1], idxs, parsed_list, key_for,
                             results, tables)
            elif kind[0] == "mldsa":
                self._run_mldsa(kind[1], idxs, parsed_list, key_for,
                                results, tables)
            elif kind[0] == "slhdsa":
                self._run_slhdsa(kind[1], idxs, parsed_list, key_for,
                                 results, tables)
            else:
                self._run_ed(idxs, parsed_list, key_for, results,
                             tables)
        if telemetry.active() is not None:
            fams = [_decision.family_for_alg(p.alg) if p is not None
                    else "unknown" for p in parsed_list]
            _decision.record_batch("tpu", results, families=fams)
        return results

    # -- bucket runners ----------------------------------------------------

    def _finish(self, idxs, parsed_list, ok_mask, results):
        for j, ok in zip(idxs, ok_mask):
            if ok:
                try:
                    results[j] = parsed_list[j].claims()
                except MalformedTokenError as e:
                    results[j] = e
            else:
                results[j] = InvalidSignatureError(
                    "no known key successfully validated the token signature"
                )

    def _run_cpu(self, idxs, parsed_list, results, tables=None):
        t = self._tables if tables is None else tables
        if not self._cpu_fallback:
            for j in idxs:
                results[j] = InvalidParameterError(
                    "token cannot be dispatched to the device engine and "
                    "CPU fallback is disabled"
                )
            return
        for j in idxs:
            p = parsed_list[j]
            last: Optional[Exception] = None
            done = False
            for i in self._candidate_indices(p, t):
                try:
                    verify_parsed(p, t.jwks[i].key)
                    results[j] = p.claims()
                    done = True
                    break
                except InvalidSignatureError as e:
                    last = e
            if not done:
                err = InvalidSignatureError(
                    "no known key successfully validated the token signature"
                )
                err.__cause__ = last
                results[j] = err

    def _hashes(self, idxs, parsed_list, hash_name):
        import hashlib

        out = []
        for j in idxs:
            p = parsed_list[j]
            # native-prepped tokens carry the digest already (computed in
            # multithreaded C++ during prepare_batch)
            pre = getattr(p, "digest", None)
            d = pre() if callable(pre) else None
            out.append(d if d else
                       hashlib.new(hash_name, p.signing_input).digest())
        return out

    def _run_rsa(self, kind, hash_name, idxs, parsed_list, key_for,
                 results, tables=None):
        from ..tpu import rsa as tpursa

        t = self._tables if tables is None else tables
        by_cls: Dict[int, List[int]] = {}
        for j in idxs:
            by_cls.setdefault(
                t.rsa_rows[key_for[j]] // _RSA_CLS_STRIDE, []).append(j)
        for cls, cidxs in sorted(by_cls.items()):
            table = t.rsa_tables[cls]
            for lo in range(0, len(cidxs), self._max_chunk):
                chunk = cidxs[lo: lo + self._max_chunk]
                pad = _pad_size(len(chunk), self._max_chunk)
                sigs = [parsed_list[j].signature for j in chunk]
                hashes_ = self._hashes(chunk, parsed_list, hash_name)
                rows = [t.rsa_rows[key_for[j]] % _RSA_CLS_STRIDE
                        for j in chunk]
                fill = pad - len(chunk)
                sigs += [b""] * fill
                hashes_ += [b"\x00" * tpursa.HASH_LEN[hash_name]] * fill
                key_idx = np.asarray(rows + [0] * fill, np.int32)
                if kind == "rs":
                    ok = tpursa.verify_pkcs1v15_batch(
                        table, sigs, hashes_, hash_name, key_idx)
                else:
                    ok = tpursa.verify_pss_batch(
                        table, sigs, hashes_, hash_name, key_idx)
                self._finish(chunk, parsed_list, ok[: len(chunk)],
                             results)

    def _run_ec(self, alg, idxs, parsed_list, key_for, results,
                tables=None):
        from ..tpu import ec as tpuec
        from ..tpu.rsa import HASH_LEN

        t = self._tables if tables is None else tables
        crv = _ES[alg]
        table = t.ec_tables[crv]
        hash_name = algs.HASH_FOR_ALG[alg]
        for lo in range(0, len(idxs), self._max_chunk):
            chunk = idxs[lo: lo + self._max_chunk]
            pad = _pad_size(len(chunk), self._max_chunk)
            sigs = [parsed_list[j].signature for j in chunk]
            hashes_ = self._hashes(chunk, parsed_list, hash_name)
            rows = [t.ec_rows[crv][key_for[j]] for j in chunk]
            fill = pad - len(chunk)
            sigs += [b"\x00" * (2 * table.coord_bytes)] * fill
            hashes_ += [b"\x00" * HASH_LEN[hash_name]] * fill
            key_idx = np.asarray(rows + [0] * fill, np.int32)
            ok = tpuec.verify_ecdsa_batch(table, sigs, hashes_, key_idx)
            self._finish(chunk, parsed_list, ok[: len(chunk)], results)

    def _run_mldsa(self, alg, idxs, parsed_list, key_for, results,
                   tables=None):
        from ..tpu import mldsa as tpumldsa

        t = self._tables if tables is None else tables
        table = t.mldsa_tables[alg]
        p = table.params
        chunk_n = self._chunk_tokens(
            max(1, ((p.l + 1) * N_COEFF * 4 + p.k * N_COEFF + 4) // 2))
        for lo in range(0, len(idxs), chunk_n):
            chunk = idxs[lo: lo + chunk_n]
            pad = _pad_size(len(chunk), chunk_n)
            sigs = [parsed_list[j].signature for j in chunk]
            msgs = [parsed_list[j].signing_input for j in chunk]
            rows = [t.mldsa_rows[alg][key_for[j]] for j in chunk]
            telemetry.count("device.mldsa.tokens", len(chunk))
            _pad_telemetry("mldsa", len(chunk), pad)
            with telemetry.span(f"dispatch.mldsa.{alg}"):
                verify = (tpumldsa.verify_mldsa_fused_pending
                          if tpumldsa.fused_enabled()
                          else tpumldsa.verify_mldsa_pending)
                ok = verify(table, sigs, msgs,
                            np.asarray(rows, np.int32), pad=pad,
                            mesh=self._mesh)()
            self._finish(chunk, parsed_list, ok[: len(chunk)], results)

    def _run_slhdsa(self, alg, idxs, parsed_list, key_for, results,
                    tables=None):
        from ..tpu import slhdsa as tpuslh

        t = self._tables if tables is None else tables
        table = t.slhdsa_tables[alg]
        p = table.params
        chunk_n = self._chunk_tokens(max(1, p.sig_size // 2))
        for lo in range(0, len(idxs), chunk_n):
            chunk = idxs[lo: lo + chunk_n]
            pad = 16
            while pad < len(chunk):
                pad *= 2
            pad = min(pad, chunk_n)
            sigs = [parsed_list[j].signature for j in chunk]
            msgs = [parsed_list[j].signing_input for j in chunk]
            rows = [t.slhdsa_rows[alg][key_for[j]] for j in chunk]
            telemetry.count("device.slhdsa.tokens", len(chunk))
            _pad_telemetry("slhdsa", len(chunk), pad)
            with telemetry.span(f"dispatch.slhdsa.{alg}"):
                ok = tpuslh.verify_slhdsa_pending(
                    table, sigs, msgs, np.asarray(rows, np.int32),
                    pad=pad, mesh=self._mesh)()
            self._finish(chunk, parsed_list, ok[: len(chunk)], results)

    def _run_ed(self, idxs, parsed_list, key_for, results,
                tables=None):
        from ..tpu import ed25519 as tpued

        t = self._tables if tables is None else tables
        table = t.ed_table
        for lo in range(0, len(idxs), self._max_chunk):
            chunk = idxs[lo: lo + self._max_chunk]
            pad = _pad_size(len(chunk), self._max_chunk)
            sigs = [parsed_list[j].signature for j in chunk]
            msgs = [parsed_list[j].signing_input for j in chunk]
            rows = [t.ed_rows[key_for[j]] for j in chunk]
            fill = pad - len(chunk)
            sigs += [b"\x00" * 64] * fill
            msgs += [b""] * fill
            key_idx = np.asarray(rows + [0] * fill, np.int32)
            ok = tpued.verify_ed25519_batch(table, sigs, msgs, key_idx)
            self._finish(chunk, parsed_list, ok[: len(chunk)], results)


class TPURemoteKeySet(KeySet):
    """Remote-JWKS-backed accelerated KeySet (key-rotation aware).

    The device analog of the reference's remote JWKS path
    (jwt/keyset.go:109-122 → coreos RemoteKeySet): keys come from a
    JWKS endpoint and live in device tables; a batch whose tokens
    present UNKNOWN kids triggers at most one refetch + table rebuild,
    and failed signatures against known kids never hit the network
    (forged tokens must not amplify into IdP fetches).

    Table rebuilds re-run the host-side window-table precompute, so
    rotation is expected to be rare relative to batch volume.
    """

    def __init__(self, jwks_url: str, jwks_ca_pem: Optional[str] = None,
                 max_chunk: int = 32768,
                 min_refresh_interval: float = 10.0, mesh=None):
        from .keyset import JSONWebKeySet

        self._remote = JSONWebKeySet(jwks_url, jwks_ca_pem)
        self._max_chunk = max_chunk
        self._min_refresh = min_refresh_interval
        self._mesh = mesh          # propagated to every table rebuild
        self._ks: Optional[TPUBatchKeySet] = None
        self._kids: set = set()
        self._last_refresh = 0.0
        import threading

        self._lock = threading.Lock()

    def _ensure(self, refresh: bool = False) -> TPUBatchKeySet:
        import time

        # Serialize fetch + rebuild: concurrent rotation triggers must
        # not double-fetch or double-build the device tables. Unknown
        # random kids (attacker-controlled) are additionally bounded by
        # a refresh cooldown AND a content check: an unchanged key set
        # never rebuilds tables.
        with self._lock:
            if self._ks is not None and refresh:
                if time.monotonic() - self._last_refresh < self._min_refresh:
                    return self._ks
            elif self._ks is not None:
                return self._ks
            if refresh:
                # Stamp BEFORE the fetch: a failing IdP (slow connect
                # timeout) must also respect the cooldown, or an
                # attacker feeding unknown kids makes every batch block
                # on a doomed fetch while holding the lock.
                self._last_refresh = time.monotonic()
            jwks = self._remote.keys(refresh=refresh)
            kids = {j.kid for j in jwks if j.kid}
            if self._ks is None:
                self._ks = TPUBatchKeySet(jwks, max_chunk=self._max_chunk,
                                          mesh=self._mesh)
                self._kids = kids
            elif kids != self._kids:
                # Hot swap (keyplane epoch bump) instead of a from-
                # scratch keyset: in-flight batches finish on their
                # tables, and the wire-rate EWMA survives the rotation.
                self._ks.swap_keys(jwks)
                self._kids = kids
            return self._ks

    def verify_signature(self, token: str) -> Dict[str, Any]:
        ks = self._ensure()
        try:
            return ks.verify_signature(token)
        except InvalidSignatureError:
            parsed = parse_jws(token)
            if parsed.kid is not None and parsed.kid not in self._kids:
                return self._ensure(refresh=True).verify_signature(token)
            raise

    def verify_batch(self, tokens: Sequence[str]) -> List[Any]:
        return self._verify_rotation_aware(tokens, raw=False)

    def verify_batch_raw(self, tokens: Sequence[str]) -> List[Any]:
        """Raw-claims analog of ``verify_batch`` (the serve default):
        accepted tokens yield their signed payload BYTES, rejects keep
        the dict path's error classes, and the same at-most-one
        rotation refetch applies."""
        return self._verify_rotation_aware(tokens, raw=True)

    def _verify_rotation_aware(self, tokens: Sequence[str],
                               raw: bool) -> List[Any]:
        ks = self._ensure()
        call = ks.verify_batch_raw if raw else ks.verify_batch
        results = call(tokens)
        missed: List[int] = []
        for i, r in enumerate(results):
            if not isinstance(r, InvalidSignatureError):
                continue
            try:
                parsed = parse_jws(tokens[i])
            except Exception:  # noqa: BLE001 - malformed keeps its error
                continue
            if parsed.kid is not None and parsed.kid not in self._kids:
                missed.append(i)
        if missed:
            telemetry.count("jwks.rotation_refetch")
            # A failed refetch (IdP hiccup, network error) must not
            # discard the whole batch's verdicts: behind AdaptiveBatcher
            # one attacker token with a random kid would otherwise fan
            # the exception out to every coalesced caller. Keep the
            # original per-token InvalidSignatureError results instead.
            try:
                ks = self._ensure(refresh=True)
                retry_call = ks.verify_batch_raw if raw else \
                    ks.verify_batch
                retry = retry_call([tokens[i] for i in missed])
            except Exception:  # noqa: BLE001 - network/IdP failure
                telemetry.count("jwks.rotation_refetch_failed")
            else:
                for i, r in zip(missed, retry):
                    results[i] = r
        return results
