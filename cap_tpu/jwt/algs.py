"""JOSE asymmetric signing algorithm registry (RFC 7518 §3.1).

Parity with jwt/algs.go:6-46: the same ten classical asymmetric
algorithms are supported, plus the post-quantum ML-DSA family (FIPS
204) under the JOSE names registered by draft-ietf-cose-dilithium
(``ML-DSA-44``/``-65``/``-87``); anything else (including ``none`` and
the HMAC family) is rejected.
"""

from __future__ import annotations

from typing import Iterable

from ..errors import UnsupportedAlgError

Alg = str

RS256: Alg = "RS256"  # RSASSA-PKCS1-v1.5 using SHA-256
RS384: Alg = "RS384"  # RSASSA-PKCS1-v1.5 using SHA-384
RS512: Alg = "RS512"  # RSASSA-PKCS1-v1.5 using SHA-512
ES256: Alg = "ES256"  # ECDSA using P-256 and SHA-256
ES384: Alg = "ES384"  # ECDSA using P-384 and SHA-384
ES512: Alg = "ES512"  # ECDSA using P-521 and SHA-512
PS256: Alg = "PS256"  # RSASSA-PSS using SHA-256 and MGF1-SHA-256
PS384: Alg = "PS384"  # RSASSA-PSS using SHA-384 and MGF1-SHA-384
PS512: Alg = "PS512"  # RSASSA-PSS using SHA-512 and MGF1-SHA-512
EdDSA: Alg = "EdDSA"  # Ed25519 using SHA-512

# Post-quantum lattice family (FIPS 204 final; JOSE names per
# draft-ietf-cose-dilithium). The whole message is absorbed by
# SHAKE256 inside the scheme, so these carry no HASH_FOR_ALG entry —
# there is no detached SHA-2 prehash step.
MLDSA44: Alg = "ML-DSA-44"  # ML-DSA-44 (NIST category 2)
MLDSA65: Alg = "ML-DSA-65"  # ML-DSA-65 (NIST category 3)
MLDSA87: Alg = "ML-DSA-87"  # ML-DSA-87 (NIST category 5)

MLDSA_ALGORITHMS = frozenset({MLDSA44, MLDSA65, MLDSA87})

# Post-quantum hash family (FIPS 205, SPHINCS+; JOSE names per
# draft-ietf-cose-sphincs-plus). Pure-hash: the scheme absorbs the
# whole message internally via SHAKE256 — no HASH_FOR_ALG entry,
# exactly like ML-DSA.
SLHDSA128S: Alg = "SLH-DSA-SHAKE-128s"  # small/slow, NIST category 1
SLHDSA128F: Alg = "SLH-DSA-SHAKE-128f"  # fast, NIST category 1

SLHDSA_ALGORITHMS = frozenset({SLHDSA128S, SLHDSA128F})

# The AKP (kty) families: parameter-set-named algs whose key object
# carries ``parameter_set`` — one membership test for the JWK /
# verify routing shared by both lattice and hash families.
PQ_ALGORITHMS = MLDSA_ALGORITHMS | SLHDSA_ALGORITHMS

SUPPORTED_ALGORITHMS = frozenset({
    RS256, RS384, RS512,
    ES256, ES384, ES512,
    PS256, PS384, PS512,
    EdDSA,
}) | PQ_ALGORITHMS

# Hash function name (hashlib) per algorithm (prehash families only:
# ML-DSA hashes internally via SHAKE and is deliberately absent).
HASH_FOR_ALG = {
    RS256: "sha256", RS384: "sha384", RS512: "sha512",
    ES256: "sha256", ES384: "sha384", ES512: "sha512",
    PS256: "sha256", PS384: "sha384", PS512: "sha512",
    EdDSA: "sha512",
}


def supported_signing_algorithm(*algs: Alg) -> None:
    """Raise UnsupportedAlgError if any given alg is not supported."""
    for a in algs:
        if a not in SUPPORTED_ALGORITHMS:
            raise UnsupportedAlgError(f"unsupported signing algorithm {a!r}")


def supported(algs: Iterable[Alg]) -> bool:
    return all(a in SUPPORTED_ALGORITHMS for a in algs)
