"""JWT verification core (capability parity with the reference's jwt/ package).

Public surface mirrors jwt/keyset.go + jwt/jwt.go + jwt/algs.go:
- :class:`Alg` registry and :func:`supported_signing_algorithm`
- :class:`KeySet` interface with :class:`StaticKeySet`,
  :class:`JSONWebKeySet`, :func:`new_oidc_discovery_keyset`
- :class:`Validator` / :class:`Expected` claims engine
- :func:`parse_public_key_pem`
- the TPU extension point: :class:`TPUBatchKeySet` (``verify_batch``)
"""

from .algs import (
    Alg,
    RS256, RS384, RS512, ES256, ES384, ES512, PS256, PS384, PS512, EdDSA,
    MLDSA44, MLDSA65, MLDSA87, MLDSA_ALGORITHMS,
    SUPPORTED_ALGORITHMS,
    supported_signing_algorithm,
)
from .jose import ParsedJWS, json_to_compact, parse_compact, parse_json, parse_jws
from .validator import DEFAULT_LEEWAY_SECONDS, Expected, Validator

# The PEM/JWKS/verify surface needs the ``cryptography`` package; it is
# re-exported lazily (same pattern as the TPU keyset below) so the
# pure-parsing core stays importable on hosts without the OpenSSL
# stack — the missing dependency then surfaces at first USE with its
# real ImportError instead of poisoning every `import cap_tpu.jwt`.
_CRYPTO_EXPORTS = {
    "parse_public_key_pem": "pem",
    "KeySet": "keyset",
    "StaticKeySet": "keyset",
    "JSONWebKeySet": "keyset",
    "new_oidc_discovery_keyset": "keyset",
}

__all__ = [
    "Alg", "RS256", "RS384", "RS512", "ES256", "ES384", "ES512",
    "PS256", "PS384", "PS512", "EdDSA",
    "MLDSA44", "MLDSA65", "MLDSA87", "MLDSA_ALGORITHMS",
    "SUPPORTED_ALGORITHMS",
    "supported_signing_algorithm",
    "ParsedJWS", "parse_compact", "parse_json", "parse_jws",
    "json_to_compact", "parse_public_key_pem",
    "KeySet", "StaticKeySet", "JSONWebKeySet", "new_oidc_discovery_keyset",
    "DEFAULT_LEEWAY_SECONDS", "Expected", "Validator",
]


def __getattr__(name):
    # TPUBatchKeySet pulls in jax; import lazily so the pure-CPU path has
    # no accelerator dependency (the reference's pure-Go-path-stays-default
    # requirement).
    if name in ("TPUBatchKeySet", "TPURemoteKeySet"):
        try:
            from . import tpu_keyset
        except ImportError as e:
            raise AttributeError(
                f"{name} requires the cap_tpu.tpu engine "
                f"(unavailable in this checkout: {e})"
            ) from e
        return getattr(tpu_keyset, name)
    if name in _CRYPTO_EXPORTS:
        import importlib

        mod = importlib.import_module(
            f".{_CRYPTO_EXPORTS[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(name)
