"""KeySet implementations.

``KeySet`` is THE seam the TPU backend plugs into — the analog of the
reference's interface at jwt/keyset.go:27-32. Three CPU implementations
mirror the reference:

- :class:`StaticKeySet` — local public keys, trial-verified in order
  (jwt/keyset.go:142-173 semantics: no kid routing).
- :class:`JSONWebKeySet` — remote JWKS URL with kid-matched key cache and
  refetch-on-miss (the behavior of coreos go-oidc's RemoteKeySet that
  jwt/keyset.go:109-139 wraps).
- :func:`new_oidc_discovery_keyset` — OIDC discovery → JWKS
  (jwt/keyset.go:49-103, including the returned-issuer equality check).

The TPU-accelerated implementation (``TPUBatchKeySet``) lives in
cap_tpu/jwt/tpu_keyset.py and adds ``verify_batch``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from .. import telemetry
from ..errors import (
    InvalidJWKSError,
    InvalidParameterError,
    InvalidSignatureError,
    NilParameterError,
    UnknownKeyIDError,
)
from ..obs import decision as _decision
from ..utils import http as _http
from .jose import ParsedJWS, parse_jws

# The crypto-backed pieces (jwk parsing, signature verification) pull
# in the ``cryptography`` package and are imported at CALL time: the
# KeySet seam itself stays importable in crypto-less environments
# (stub fleets, decision-layer tests), matching the lazy exports in
# cap_tpu.jwt.__init__. Annotations are postponed (future import), so
# the JWK name is only needed when type checkers look.
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from .jwk import JWK


class KeySet:
    """Verifies JWT signatures; returns the verified (still unvalidated) claims.

    Subclasses implement :meth:`verify_signature`. Implementations that
    can batch (the TPU backend) additionally implement
    :meth:`verify_batch`; the default loops over tokens.
    """

    def verify_signature(self, token: str) -> Dict[str, Any]:
        raise NotImplementedError

    def verify_batch(self, tokens: Sequence[str]) -> List[Any]:
        """Verify many tokens; returns one entry per token: either the
        claims dict or the exception that token failed with. Never raises
        for per-token failures."""
        t0 = time.perf_counter()
        out: List[Any] = []
        for t in tokens:
            try:
                out.append(self.verify_signature(t))
            except Exception as e:  # noqa: BLE001 - per-token error channel
                out.append(e)
        # CPU-oracle surface of the decision stream (the batched TPU
        # engine overrides verify_batch and records surface "tpu").
        _decision.record_batch("oracle", out, tokens=tokens,
                               latency_s=time.perf_counter() - t0)
        return out


class StaticKeySet(KeySet):
    """KeySet backed by a local list of public keys.

    Matches the reference's trial-verification semantics: every key is
    tried in order until one verifies (O(keys) signature checks worst
    case, no kid routing).
    """

    def __init__(self, public_keys: Sequence[object]):
        if not public_keys:
            raise NilParameterError("public keys are required")
        self._keys = list(public_keys)

    def verify_signature(self, token: str) -> Dict[str, Any]:
        from .verify import verify_parsed

        parsed = parse_jws(token)
        last_err: Optional[Exception] = None
        for key in self._keys:
            try:
                verify_parsed(parsed, key)
                return parsed.claims()
            except InvalidSignatureError as e:
                last_err = e
        raise InvalidSignatureError(
            "no known key successfully validated the token signature"
        ) from last_err


class JSONWebKeySet(KeySet):
    """KeySet backed by a remote JWKS endpoint.

    Keys are cached; a verification that finds no usable cached key for
    the token's kid triggers one refetch (key-rotation handling), the
    same observable behavior as the coreos RemoteKeySet the reference
    wraps. Thread-safe.

    ``refresh_cooldown_s``: minimum interval between MISS-triggered
    refetches. Repeated unknown-kid lookups inside the window raise
    without touching the network — attacker tokens carrying random
    kids must not amplify 1:1 into IdP fetches (DoS guard). The
    initial cache fill and explicit ``keys(refresh=True)`` calls are
    not throttled.
    """

    def __init__(self, jwks_url: str, jwks_ca_pem: Optional[str] = None,
                 refresh_cooldown_s: float = 10.0):
        if not jwks_url:
            raise NilParameterError("jwks_url is required")
        self._url = jwks_url
        self._ssl_ctx = _http.ssl_context_for_ca(jwks_ca_pem)
        self._lock = threading.Lock()
        self._keys: Optional[List[JWK]] = None
        self._refresh_cooldown = refresh_cooldown_s
        self._last_miss_refresh = float("-inf")

    # -- key cache ---------------------------------------------------------

    def _fetch(self) -> List[JWK]:
        status, body, _ = _http.get(self._url, self._ssl_ctx)
        if status != 200:
            raise InvalidJWKSError(f"jwks fetch failed: status {status}")
        try:
            doc = json.loads(body)
        except ValueError as e:
            raise InvalidJWKSError(f"jwks is not valid JSON: {e}") from e
        if not isinstance(doc, dict):
            raise InvalidJWKSError("jwks is not a JSON object")
        from .jwk import parse_jwks

        keys = parse_jwks(doc)
        with self._lock:
            self._keys = keys
        return keys

    def keys(self, refresh: bool = False) -> List[JWK]:
        with self._lock:
            cached = self._keys
        if cached is None or refresh:
            return self._fetch()
        return cached

    # -- verification ------------------------------------------------------

    @staticmethod
    def _candidates(keys: "List[JWK]", parsed: ParsedJWS) -> "List[JWK]":
        from .verify import key_matches_alg

        out = []
        for jwk in keys:
            if jwk.use not in (None, "", "sig"):
                continue
            if parsed.kid is not None and jwk.kid is not None and jwk.kid != parsed.kid:
                continue
            if not key_matches_alg(jwk.key, parsed.alg):
                continue
            out.append(jwk)
        return out

    def verify_signature(self, token: str) -> Dict[str, Any]:
        from .verify import verify_parsed

        parsed = parse_jws(token)
        keys = self.keys()
        candidates = self._candidates(keys, parsed)
        last_err: Optional[Exception] = None
        for jwk in candidates:
            try:
                verify_parsed(parsed, jwk.key)
                return parsed.claims()
            except InvalidSignatureError as e:
                last_err = e
        if not candidates:
            # kid cache miss only → one refetch (key rotation). A failed
            # verification against cached candidates must NOT hit the
            # network — forged tokens would amplify into IdP fetches.
            now = time.monotonic()
            with self._lock:
                cooled = (now - self._last_miss_refresh
                          < self._refresh_cooldown)
                if not cooled:
                    # Stamp BEFORE the fetch: a slow or failing IdP
                    # must also respect the cooldown, or every
                    # unknown-kid token blocks on a doomed fetch.
                    self._last_miss_refresh = now
            if cooled:
                telemetry.count("jwks.refresh_suppressed")
                if parsed.kid is not None:
                    raise UnknownKeyIDError(
                        "no key matches kid (refresh cooldown active)"
                    ) from last_err
                raise InvalidSignatureError(
                    "failed to verify id token signature") from last_err
            keys = self.keys(refresh=True)
            refreshed = self._candidates(keys, parsed)
            for jwk in refreshed:
                try:
                    verify_parsed(parsed, jwk.key)
                    return parsed.claims()
                except InvalidSignatureError as e:
                    last_err = e
            if not refreshed and parsed.kid is not None:
                # Even the freshly fetched set has no key for this kid:
                # provably unknown (distinct reason class in telemetry —
                # a rotation gap, not a forgery).
                raise UnknownKeyIDError(
                    "no key matches kid after refresh"
                ) from last_err
        raise InvalidSignatureError(
            "failed to verify id token signature"
        ) from last_err


def new_oidc_discovery_keyset(issuer: str,
                              issuer_ca_pem: Optional[str] = None) -> JSONWebKeySet:
    """Build a JWKS keyset from an issuer's OIDC discovery document.

    Fetches ``{issuer}/.well-known/openid-configuration``, requires the
    document's ``issuer`` to equal the requested issuer, and returns a
    :class:`JSONWebKeySet` on the advertised ``jwks_uri``.

    Discovery failures (bad status, non-JSON document, issuer mismatch)
    raise :class:`InvalidIssuerError` — the same taxonomy the oidc
    Provider uses for its discovery step.
    """
    if not issuer:
        raise NilParameterError("issuer is required")
    ctx = _http.ssl_context_for_ca(issuer_ca_pem)
    doc = _http.fetch_discovery(issuer, ctx)
    jwks_uri = doc.get("jwks_uri")
    if not isinstance(jwks_uri, str) or not jwks_uri:
        raise InvalidParameterError("discovery document missing jwks_uri")
    return JSONWebKeySet(jwks_uri, jwks_ca_pem=issuer_ca_pem)
