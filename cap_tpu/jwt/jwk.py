"""JWK (RFC 7517/7518) parse and serialize.

The reference gets this from coreos go-oidc's RemoteKeySet; here it is
implemented directly: RSA (kty=RSA: n,e), EC (kty=EC: crv,x,y on
P-256/P-384/P-521), OKP Ed25519 (kty=OKP, crv=Ed25519: x), and the
post-quantum ML-DSA family (kty=AKP: alg, pub — the Algorithm Key
Pair type from draft-ietf-cose-dilithium / draft-ietf-jose-pqc).

``x5c`` certificate chains (RFC 7517 §4.7) are accepted the way the
go-jose JSONWebKey the reference wraps accepts them (jwt/keyset.go:
109-122): a key whose material arrives only as a certificate chain
takes its public key from the first certificate's SPKI, and a key
carrying BOTH parameters and a chain must have them agree.

Dependency posture: the ``cryptography`` package is imported at CALL
time, per key type. AKP keys never need it (the ML-DSA stack is
dependency-free), and EC keys fall back to the pure-integer
``HostECPublicKey`` (with an explicit on-curve check) where the
OpenSSL stack is absent — that is what lets the full ES256→ML-DSA
hybrid-migration path run on crypto-less hosts. RSA/OKP keys and x5c
chains still require ``cryptography`` and surface its ImportError at
first use, matching the package's lazy-export stance.
"""

from __future__ import annotations

import base64
import binascii
from typing import Any, Dict, List, Optional

from ..errors import InvalidJWKSError
from .jose import b64url_decode, b64url_encode

_CURVES = {
    "P-256": ("secp256r1", 32),
    "P-384": ("secp384r1", 48),
    "P-521": ("secp521r1", 66),
}
_CURVE_NAME_FOR_KEY = {"secp256r1": "P-256", "secp384r1": "P-384",
                       "secp521r1": "P-521"}

# SEC 2 curve b constants for the dependency-free on-curve check
# (a = -3 on every NIST curve). tests/test_mldsa.py pins each base
# point against these, so a transcription error cannot survive CI.
_CURVE_B = {
    "P-256": 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B,  # noqa: E501
    "P-384": 0xB3312FA7E23EE7E4988E056BE3F82D19181D9C6EFE8141120314088F5013875AC656398D8A2ED19D2A85C8EDD3EC2AEF,  # noqa: E501
    "P-521": 0x0051953EB9618E1C9A1F929A21A0B68540EEA2DA725B99B315F3B8B489918EF109E156193951EC7E937B1652C0BD3BB1BF073573DF883D2C34F1EF451FD46B503F00,  # noqa: E501
}


def _crypto():
    """The cryptography key-type modules, or None when unavailable."""
    try:
        from cryptography.hazmat.primitives.asymmetric import (
            ec,
            ed25519,
            rsa,
        )
    except ImportError:
        return None
    return ec, ed25519, rsa


class JWK:
    """One JSON Web Key: the parsed public key plus JOSE metadata."""

    def __init__(self, key, kid: Optional[str] = None, alg: Optional[str] = None,
                 use: Optional[str] = None):
        self.key = key
        self.kid = kid
        self.alg = alg
        self.use = use

    def __repr__(self) -> str:
        return f"JWK(kid={self.kid!r}, alg={self.alg!r}, type={type(self.key).__name__})"


def _b64_uint(data: Dict[str, Any], field: str) -> int:
    v = data.get(field)
    if not isinstance(v, str):
        raise InvalidJWKSError(f"jwk missing field {field!r}")
    return int.from_bytes(b64url_decode(v), "big")


def _x5c_public_key(data: Dict[str, Any]):
    """Public key from the first x5c certificate, or None when absent.

    Per RFC 7517 §4.7 each entry is STANDARD base64 (not base64url) of
    a DER certificate; the first entry is the key's own certificate.
    EVERY entry must decode and parse as a certificate — go-jose DER-
    parses the whole chain up front, so a garbage intermediate/root
    entry rejects the key even though only the leaf's SPKI is used; a
    present-but-invalid chain is an error, never silently truncated.
    """
    x5c = data.get("x5c")
    if x5c is None:
        return None
    from cryptography import x509
    from cryptography.hazmat.primitives.asymmetric import ec, ed25519, rsa

    if not isinstance(x5c, list) or not x5c or not all(
            isinstance(c, str) for c in x5c):
        raise InvalidJWKSError("jwk x5c must be a non-empty string array")
    certs = []
    for i, entry in enumerate(x5c):
        try:
            der = base64.b64decode(entry, validate=True)
            certs.append(x509.load_der_x509_certificate(der))
        except (binascii.Error, ValueError) as err:
            raise InvalidJWKSError(
                f"invalid x5c certificate at index {i}: {err}") from err
    key = certs[0].public_key()
    if not isinstance(key, (rsa.RSAPublicKey, ec.EllipticCurvePublicKey,
                            ed25519.Ed25519PublicKey)):
        raise InvalidJWKSError(
            "x5c certificate carries an unsupported key type")
    return key


def _keys_equal(a, b) -> bool:
    if type(a) is not type(b):
        return False
    from cryptography.hazmat.primitives.asymmetric import ed25519

    if isinstance(a, ed25519.Ed25519PublicKey):
        from cryptography.hazmat.primitives.serialization import (
            Encoding, PublicFormat,
        )
        return (a.public_bytes(Encoding.Raw, PublicFormat.Raw)
                == b.public_bytes(Encoding.Raw, PublicFormat.Raw))
    return a.public_numbers() == b.public_numbers()


def _parse_ec_host(data: Dict[str, Any], crv: str):
    """EC parse without the OpenSSL stack: pure-integer key with an
    explicit on-curve check (cryptography validates the same thing in
    its constructor — the rejection surface must not silently widen
    when the dependency is absent)."""
    from ..tpu.ec import _CURVE_INTS, HostECPublicKey

    x = _b64_uint(data, "x")
    y = _b64_uint(data, "y")
    p = _CURVE_INTS[crv]["p"]
    if not (0 <= x < p and 0 <= y < p) or \
            (y * y - (x * x * x - 3 * x + _CURVE_B[crv])) % p != 0:
        raise InvalidJWKSError(
            f"invalid EC jwk: point is not on curve {crv}")
    return HostECPublicKey(crv, x, y)


def _parse_akp(data: Dict[str, Any]):
    """kty=AKP (ML-DSA / SLH-DSA): the parameter set rides the
    REQUIRED alg member and the public key is the FIPS 204/205 pk
    encoding in ``pub`` (draft-ietf-cose-dilithium /
    draft-ietf-cose-sphincs-plus JOSE serialization)."""
    from ..tpu.mldsa import MLDSA_ALGS, MLDSAPublicKey
    from ..tpu.slhdsa import SLHDSA_ALGS, SLHDSAPublicKey

    alg = data.get("alg")
    if alg in MLDSA_ALGS:
        key_cls = MLDSAPublicKey
    elif alg in SLHDSA_ALGS:
        key_cls = SLHDSAPublicKey
    else:
        raise InvalidJWKSError(
            f"AKP jwk requires alg in "
            f"{sorted(MLDSA_ALGS) + sorted(SLHDSA_ALGS)}, got {alg!r}")
    raw = data.get("pub")
    if not isinstance(raw, str):
        raise InvalidJWKSError("AKP jwk missing field 'pub'")
    try:
        key = key_cls(alg, b64url_decode(raw))
    except ValueError as err:
        raise InvalidJWKSError(f"invalid AKP jwk: {err}") from err
    return key


def parse_jwk(data: Dict[str, Any]) -> JWK:
    """Parse one JWK dict into a JWK with a usable public key."""
    kty = data.get("kty")
    if kty == "AKP":
        # Post-quantum path first: never touches the OpenSSL stack.
        key = _parse_akp(data)
        kid = data.get("kid") if isinstance(data.get("kid"), str) else None
        alg = data.get("alg") if isinstance(data.get("alg"), str) else None
        use = data.get("use") if isinstance(data.get("use"), str) else None
        return JWK(key, kid=kid, alg=alg, use=use)

    crypto = _crypto()
    cert_key = _x5c_public_key(data) if (data.get("x5c") is not None
                                         or crypto is not None) else None
    key = None
    if kty == "RSA":
        if crypto is None:
            raise ImportError(
                "parsing RSA JWKs requires the 'cryptography' package")
        ec, ed25519, rsa = crypto
        # presence-gated, not type-gated: a MALFORMED n/e must reject
        # (as go-jose does), never silently defer to the x5c key
        if "n" in data or "e" in data or cert_key is None:
            n = _b64_uint(data, "n")
            e = _b64_uint(data, "e")
            try:
                key = rsa.RSAPublicNumbers(e, n).public_key()
            except ValueError as err:
                raise InvalidJWKSError(f"invalid RSA jwk: {err}") from err
        expected_type = rsa.RSAPublicKey
    elif kty == "EC":
        crv = data.get("crv")
        if "x" in data or "y" in data or cert_key is None:
            if crv not in _CURVES:
                raise InvalidJWKSError(f"unsupported EC curve {crv!r}")
            if crypto is None:
                return JWK(
                    _parse_ec_host(data, crv),
                    kid=data.get("kid") if isinstance(data.get("kid"),
                                                      str) else None,
                    alg=data.get("alg") if isinstance(data.get("alg"),
                                                      str) else None,
                    use=data.get("use") if isinstance(data.get("use"),
                                                      str) else None)
            ec, ed25519, rsa = crypto
            curve_cls = {"secp256r1": ec.SECP256R1,
                         "secp384r1": ec.SECP384R1,
                         "secp521r1": ec.SECP521R1}[_CURVES[crv][0]]
            x = _b64_uint(data, "x")
            y = _b64_uint(data, "y")
            try:
                key = ec.EllipticCurvePublicNumbers(
                    x, y, curve_cls()).public_key()
            except ValueError as err:
                raise InvalidJWKSError(f"invalid EC jwk: {err}") from err
        elif crv is not None and crv not in _CURVES:
            raise InvalidJWKSError(f"unsupported EC curve {crv!r}")
        if crypto is None:
            raise ImportError(
                "parsing x5c-only EC JWKs requires the 'cryptography' "
                "package")
        ec, ed25519, rsa = crypto
        expected_type = ec.EllipticCurvePublicKey
    elif kty == "OKP":
        if data.get("crv") != "Ed25519":
            raise InvalidJWKSError(f"unsupported OKP curve {data.get('crv')!r}")
        if crypto is None:
            raise ImportError(
                "parsing OKP JWKs requires the 'cryptography' package")
        ec, ed25519, rsa = crypto
        if "x" in data or cert_key is None:
            raw = data.get("x")
            if not isinstance(raw, str):
                raise InvalidJWKSError("jwk missing field 'x'")
            try:
                key = ed25519.Ed25519PublicKey.from_public_bytes(
                    b64url_decode(raw))
            except ValueError as err:
                raise InvalidJWKSError(
                    f"invalid Ed25519 jwk: {err}") from err
        expected_type = ed25519.Ed25519PublicKey
    else:
        raise InvalidJWKSError(f"unsupported jwk kty {kty!r}")

    if cert_key is not None:
        ec, ed25519, rsa = crypto
        if not isinstance(cert_key, expected_type):
            raise InvalidJWKSError(
                "x5c certificate key type does not match jwk kty")
        if isinstance(cert_key, ec.EllipticCurvePublicKey):
            cert_crv = _CURVE_NAME_FOR_KEY.get(cert_key.curve.name)
            if cert_crv is None:
                raise InvalidJWKSError(
                    f"unsupported EC curve {cert_key.curve.name!r} in x5c")
            declared = data.get("crv")
            if declared is not None and declared != cert_crv:
                raise InvalidJWKSError(
                    "jwk crv does not match x5c certificate curve")
        if key is None:
            key = cert_key          # material arrived only via x5c
        elif not _keys_equal(key, cert_key):
            raise InvalidJWKSError(
                "jwk parameters do not match x5c certificate key")

    kid = data.get("kid") if isinstance(data.get("kid"), str) else None
    alg = data.get("alg") if isinstance(data.get("alg"), str) else None
    use = data.get("use") if isinstance(data.get("use"), str) else None
    return JWK(key, kid=kid, alg=alg, use=use)


def parse_jwks(document: Dict[str, Any]) -> List[JWK]:
    """Parse a JWKS document ``{"keys": [...]}``."""
    keys = document.get("keys")
    if not isinstance(keys, list):
        raise InvalidJWKSError("jwks document missing 'keys' array")
    out: List[JWK] = []
    for entry in keys:
        if not isinstance(entry, dict):
            raise InvalidJWKSError("jwks entry is not an object")
        out.append(parse_jwk(entry))
    return out


def _uint_b64(v: int, length: Optional[int] = None) -> str:
    n = length if length is not None else max(1, (v.bit_length() + 7) // 8)
    return b64url_encode(v.to_bytes(n, "big"))


def serialize_public_key(key, kid: Optional[str] = None,
                         alg: Optional[str] = None) -> Dict[str, Any]:
    """Serialize a public key into a JWK dict (used by the fake IdP and tests)."""
    out: Dict[str, Any] = {"use": "sig"}
    if kid:
        out["kid"] = kid
    if alg:
        out["alg"] = alg
    pset = getattr(key, "parameter_set", None)
    if pset is not None:                       # MLDSAPublicKey → AKP
        out.update({"kty": "AKP", "alg": pset,
                    "pub": b64url_encode(key.pk)})
        return out
    crv_host = getattr(key, "curve_name", None)
    if crv_host is not None:                   # HostECPublicKey → EC
        nums = key.public_numbers()
        size = _CURVES[crv_host][1]
        out.update({"kty": "EC", "crv": crv_host,
                    "x": _uint_b64(nums.x, size),
                    "y": _uint_b64(nums.y, size)})
        return out
    from cryptography.hazmat.primitives.asymmetric import ec, ed25519, rsa

    if isinstance(key, rsa.RSAPublicKey):
        nums = key.public_numbers()
        out.update({"kty": "RSA", "n": _uint_b64(nums.n), "e": _uint_b64(nums.e)})
    elif isinstance(key, ec.EllipticCurvePublicKey):
        nums = key.public_numbers()
        crv = _CURVE_NAME_FOR_KEY[key.curve.name]
        size = _CURVES[crv][1]
        out.update({
            "kty": "EC", "crv": crv,
            "x": _uint_b64(nums.x, size), "y": _uint_b64(nums.y, size),
        })
    elif isinstance(key, ed25519.Ed25519PublicKey):
        from cryptography.hazmat.primitives.serialization import (
            Encoding, PublicFormat,
        )
        raw = key.public_bytes(Encoding.Raw, PublicFormat.Raw)
        out.update({"kty": "OKP", "crv": "Ed25519", "x": b64url_encode(raw)})
    else:
        raise InvalidJWKSError(f"cannot serialize key type {type(key).__name__}")
    return out
