"""JWT claims validation engine.

Parity with the reference's ``Validator.Validate`` (jwt/jwt.go:95-202):
signature verification through the KeySet seam, then alg-header
validation, then registered-claims validation with the same defaulting
and leeway rules:

- at least one of iat/exp/nbf must be present;
- missing exp defaults to max(iat, nbf) + expiration leeway;
- missing nbf defaults to iat, else exp − not-before leeway;
- leeways: 0/None → default (150s; clock-skew 60s), negative → none;
- expected alg list defaults to [RS256].
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..errors import (
    InvalidAudienceError,
    InvalidIssuedAtError,
    InvalidIssuerError,
    InvalidNotBeforeError,
    InvalidParameterError,
    InvalidSignatureError,
    ExpiredTokenError,
    MalformedTokenError,
    MissingClaimError,
    NilParameterError,
    UnsupportedAlgError,
)
from . import algs
from ..errors import CapError
from .jose import peek_alg
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # annotation-only: keyset pulls in the crypto stack
    from .keyset import KeySet

# Leeway used by default for "nbf" and "exp" (reference: jwt/jwt.go:16).
DEFAULT_LEEWAY_SECONDS = 150
# Default clock-skew leeway (go-jose jwt.DefaultLeeway = 1 minute).
DEFAULT_CLOCK_SKEW_SECONDS = 60


@dataclass
class Expected:
    """Expected claim values to assert when validating a JWT.

    Leeway fields are seconds: None or 0 → default, negative → no leeway
    (same encoding as the reference's time.Duration fields,
    jwt/jwt.go:60-83).
    """

    issuer: str = ""
    subject: str = ""
    id: str = ""
    audiences: List[str] = field(default_factory=list)
    signing_algorithms: List[str] = field(default_factory=list)
    not_before_leeway: Optional[float] = None
    expiration_leeway: Optional[float] = None
    clock_skew_leeway: Optional[float] = None
    now: Optional[Callable[[], float]] = None  # returns Unix seconds


def _effective_leeway(value: Optional[float], default: float) -> float:
    if value is None or value == 0:
        return default
    if value < 0:
        return 0.0
    return value


def _numeric_claim(claims: Dict[str, Any], name: str) -> Optional[float]:
    v = claims.get(name)
    if v is None:
        return None
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise MalformedTokenError(f"claim {name!r} is not a number")
    return float(v)


def _string_claim(claims: Dict[str, Any], name: str) -> str:
    v = claims.get(name)
    if v is None:
        return ""
    if not isinstance(v, str):
        raise MalformedTokenError(f"claim {name!r} is not a string")
    return v


def audience_claim(claims: Dict[str, Any]) -> List[str]:
    """Normalize the aud claim to a list of strings (RFC 7519 §4.1.3)."""
    v = claims.get("aud")
    if v is None:
        return []
    if isinstance(v, str):
        return [v]
    if isinstance(v, list) and all(isinstance(x, str) for x in v):
        return list(v)
    raise MalformedTokenError("claim 'aud' is not a string or string array")


def validate_audience(expected_audiences: Sequence[str],
                      aud_claim: Sequence[str]) -> None:
    """Error unless aud_claim intersects expected (empty expected → skip)."""
    if not expected_audiences:
        return
    if any(a in aud_claim for a in expected_audiences):
        return
    raise InvalidAudienceError(
        "audience claim does not match any expected audience"
    )


def validate_signing_algorithm(token: str,
                               expected_algorithms: Sequence[str]) -> None:
    """Check the JWS alg header against the expected list (default RS256).

    Decodes only the header and signature segments — the payload (the
    bulk of the token) was already decoded by the KeySet verify step, so
    re-decoding it here would double the hot-path parse work.
    """
    algs.supported_signing_algorithm(*expected_algorithms)
    alg = peek_alg(token)  # raises on malformed/unsigned
    expected = list(expected_algorithms) or [algs.RS256]
    if alg not in expected:
        raise UnsupportedAlgError("token signed with unexpected algorithm")


def validate_claims(all_claims: Dict[str, Any], expected: Expected) -> None:
    """Registered-claims validation (time windows, iss/sub/jti/aud)."""
    iat = _numeric_claim(all_claims, "iat") or 0.0
    exp = _numeric_claim(all_claims, "exp") or 0.0
    nbf = _numeric_claim(all_claims, "nbf") or 0.0

    if iat == 0 and exp == 0 and nbf == 0:
        raise MissingClaimError(
            "no issued at (iat), not before (nbf), or expiration time (exp) "
            "claims in token"
        )

    if exp == 0:
        latest_start = max(iat, nbf)
        exp = latest_start + _effective_leeway(
            expected.expiration_leeway, DEFAULT_LEEWAY_SECONDS
        )
    if nbf == 0:
        if iat != 0:
            nbf = iat
        else:
            nbf = exp - _effective_leeway(
                expected.not_before_leeway, DEFAULT_LEEWAY_SECONDS
            )

    cks = _effective_leeway(expected.clock_skew_leeway, DEFAULT_CLOCK_SKEW_SECONDS)

    if expected.issuer and expected.issuer != _string_claim(all_claims, "iss"):
        raise InvalidIssuerError("invalid issuer (iss) claim")
    if expected.subject and expected.subject != _string_claim(all_claims, "sub"):
        raise InvalidParameterError("invalid subject (sub) claim")
    if expected.id and expected.id != _string_claim(all_claims, "jti"):
        raise InvalidParameterError("invalid ID (jti) claim")
    validate_audience(expected.audiences, audience_claim(all_claims))

    now = expected.now() if expected.now is not None else _time.time()
    if now + cks < nbf:
        raise InvalidNotBeforeError(
            "invalid not before (nbf) claim: token not yet valid"
        )
    if now - cks > exp:
        raise ExpiredTokenError(
            "invalid expiration time (exp) claim: token is expired"
        )
    if now + cks < iat:
        raise InvalidIssuedAtError(
            "invalid issued at (iat) claim: token issued in the future"
        )


class Validator:
    """Validates JWTs: signature via the KeySet, then claims vs Expected."""

    def __init__(self, keyset: KeySet):
        if keyset is None:
            raise NilParameterError("keySet must not be None")
        self.keyset = keyset

    def validate(self, token: str, expected: Expected | None = None) -> Dict[str, Any]:
        """Verify-then-validate one JWT; returns all claims on success."""
        expected = expected or Expected()
        try:
            all_claims = self.keyset.verify_signature(token)
        except CapError:
            # Preserve the taxonomy (MalformedTokenError, UnsupportedAlgError,
            # InvalidSignatureError, ...) so isinstance-based handling — the
            # analog of the reference's errors.Is over %w wraps — works.
            raise
        except Exception as e:
            raise InvalidSignatureError(
                f"error verifying token signature: {e}"
            ) from e
        validate_signing_algorithm(token, expected.signing_algorithms)
        validate_claims(all_claims, expected)
        return all_claims

    def validate_batch(self, tokens: Sequence[str],
                       expected: Expected | None = None) -> List[Any]:
        """Batched verify-then-validate.

        Signature verification goes through the KeySet's batch path (the
        TPU engine when the keyset is a TPUBatchKeySet); claims are then
        validated per token. Returns one entry per token: the claims dict
        or the exception that token failed with.
        """
        expected = expected or Expected()
        results = self.keyset.verify_batch(tokens)
        out: List[Any] = []
        for token, res in zip(tokens, results):
            if isinstance(res, CapError):
                out.append(res)
                continue
            if isinstance(res, Exception):
                out.append(InvalidSignatureError(
                    f"error verifying token signature: {res}"
                ))
                continue
            try:
                validate_signing_algorithm(token, expected.signing_algorithms)
                validate_claims(res, expected)
                out.append(res)
            except Exception as e:  # noqa: BLE001 - per-token error channel
                out.append(e)
        return out
