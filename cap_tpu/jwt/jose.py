"""JOSE compact-serialization (JWS) parsing.

The reference delegates to go-jose's ``jose.ParseSigned``
(jwt/jwt.go:212, jwt/keyset.go:155); this is a from-scratch strict
parser for the compact form ``b64url(header).b64url(payload).b64url(sig)``
per RFC 7515:
- exactly three dot-separated segments;
- base64url *without* padding, no whitespace;
- the protected header must be a JSON object;
- the ``alg`` header must be present and a string.

A native C++ batch version of this parse lives in cap_tpu/runtime; this
module is the reference implementation and single-token path.
"""

from __future__ import annotations

import base64
import binascii
import json
from dataclasses import dataclass
from typing import Any, Dict

from ..errors import MalformedTokenError, TokenNotSignedError

_B64URL_CHARS = frozenset(
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_"
)


def b64url_decode(segment: str) -> bytes:
    """Strict unpadded base64url decode (RFC 7515 §2)."""
    if not set(segment) <= _B64URL_CHARS:
        raise MalformedTokenError("illegal base64url character")
    if len(segment) % 4 == 1:
        raise MalformedTokenError("illegal base64url length")
    pad = "=" * (-len(segment) % 4)
    try:
        return base64.urlsafe_b64decode(segment + pad)
    except (binascii.Error, ValueError) as e:
        raise MalformedTokenError(f"invalid base64url segment: {e}") from e


def b64url_encode(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


@dataclass(frozen=True)
class ParsedJWS:
    """A parsed (but unverified) compact JWS."""

    header: Dict[str, Any]       # decoded protected header
    payload: bytes               # decoded payload bytes
    signature: bytes             # decoded signature bytes
    signing_input: bytes         # ascii(b64(header) + "." + b64(payload))

    @property
    def alg(self) -> str:
        return self.header["alg"]

    @property
    def kid(self) -> str | None:
        kid = self.header.get("kid")
        return kid if isinstance(kid, str) else None

    def claims(self) -> Dict[str, Any]:
        """Decode the payload as a JSON claims object (unverified)."""
        try:
            claims = json.loads(self.payload)
        except (ValueError, UnicodeDecodeError) as e:
            raise MalformedTokenError(f"payload is not valid JSON: {e}") from e
        if not isinstance(claims, dict):
            raise MalformedTokenError("payload is not a JSON object")
        return claims


def _split_and_header(token: str):
    """Shared strict structural parse: split, decode+check the header.

    Returns (header_dict, raw_header, raw_payload, raw_sig). Single
    source of truth for the structural rules — peek_alg, parse_compact,
    and the C++ runtime conformance tests all key off this behavior.
    """
    if not isinstance(token, str) or not token:
        raise MalformedTokenError("token is empty")
    parts = token.split(".")
    if len(parts) != 3:
        raise MalformedTokenError(
            f"compact JWS must have 3 segments, found {len(parts)}"
        )
    raw_header, raw_payload, raw_sig = parts
    header_bytes = b64url_decode(raw_header)
    try:
        header = json.loads(header_bytes)
    except (ValueError, UnicodeDecodeError) as e:
        raise MalformedTokenError(f"protected header is not valid JSON: {e}") from e
    if not isinstance(header, dict):
        raise MalformedTokenError("protected header is not a JSON object")
    alg = header.get("alg")
    if not isinstance(alg, str) or not alg:
        raise MalformedTokenError("protected header missing alg parameter")
    return header, raw_header, raw_payload, raw_sig


def peek_alg(token: str) -> str:
    """Return the alg header of a compact JWS, enforcing the same
    structural rules as :func:`parse_compact` but without decoding the
    payload segment (cheap header-only inspection)."""
    header, _, raw_payload, raw_sig = _split_and_header(token)
    # Validate payload/signature segment charsets without decoding bytes.
    for seg in (raw_payload, raw_sig):
        if not set(seg) <= _B64URL_CHARS or len(seg) % 4 == 1:
            raise MalformedTokenError("illegal base64url segment")
    if not raw_sig:
        raise TokenNotSignedError("token must be signed")
    return header["alg"]


def parse_compact(token: str) -> ParsedJWS:
    """Parse a compact-serialization JWS without verifying it."""
    header, raw_header, raw_payload, raw_sig = _split_and_header(token)
    payload = b64url_decode(raw_payload)
    signature = b64url_decode(raw_sig)
    if len(signature) == 0:
        raise TokenNotSignedError("token must be signed")
    signing_input = (raw_header + "." + raw_payload).encode("ascii")
    return ParsedJWS(
        header=header,
        payload=payload,
        signature=signature,
        signing_input=signing_input,
    )
