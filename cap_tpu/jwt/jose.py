"""JOSE (JWS) parsing: compact and JSON serializations.

The reference delegates to go-jose's ``jose.ParseSigned``
(jwt/jwt.go:212, jwt/keyset.go:155); this is a from-scratch strict
parser. Compact form ``b64url(header).b64url(payload).b64url(sig)``
per RFC 7515:
- exactly three dot-separated segments;
- base64url *without* padding, no whitespace;
- the protected header must be a JSON object;
- the ``alg`` header must be present and a string;
- any ``crit`` protected header is rejected (go-jose rejects every
  JWS bearing one — "unsupported crit header" — and this framework
  matches that verdict bit-for-bit, jwt/jwt.go:212 via ParseSigned).

The JSON serialization (RFC 7515 §7.2, both flattened and general
forms) is accepted with exactly ONE signature, matching the
reference's post-parse check (jwt/jwt.go:212-227): go-jose
auto-detects a leading ``{`` and the reference then requires
``len(parsedJWT.Headers) == 1``.

A native C++ batch version of the compact parse lives in
cap_tpu/runtime; this module is the reference implementation and
single-token path. ``parse_jws`` dispatches on serialization form;
``json_to_compact`` re-serializes a JSON-form token so the batch
paths (native prep, TPU packing, serve) stay compact-only.
"""

from __future__ import annotations

import base64
import binascii
import json
from dataclasses import dataclass
from typing import Any, Dict

from ..errors import MalformedTokenError, TokenNotSignedError

_B64URL_CHARS = frozenset(
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_"
)


def b64url_decode(segment: str) -> bytes:
    """Strict unpadded base64url decode (RFC 7515 §2)."""
    if not set(segment) <= _B64URL_CHARS:
        raise MalformedTokenError("illegal base64url character")
    if len(segment) % 4 == 1:
        raise MalformedTokenError("illegal base64url length")
    pad = "=" * (-len(segment) % 4)
    try:
        return base64.urlsafe_b64decode(segment + pad)
    except (binascii.Error, ValueError) as e:
        raise MalformedTokenError(f"invalid base64url segment: {e}") from e


def b64url_encode(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


@dataclass(frozen=True)
class ParsedJWS:
    """A parsed (but unverified) compact JWS."""

    header: Dict[str, Any]       # decoded protected header
    payload: bytes               # decoded payload bytes
    signature: bytes             # decoded signature bytes
    signing_input: bytes         # ascii(b64(header) + "." + b64(payload))

    @property
    def alg(self) -> str:
        return self.header["alg"]

    @property
    def kid(self) -> str | None:
        kid = self.header.get("kid")
        return kid if isinstance(kid, str) else None

    def claims(self) -> Dict[str, Any]:
        """Decode the payload as a JSON claims object (unverified)."""
        try:
            claims = json.loads(self.payload)
        except (ValueError, UnicodeDecodeError) as e:
            raise MalformedTokenError(f"payload is not valid JSON: {e}") from e
        if not isinstance(claims, dict):
            raise MalformedTokenError("payload is not a JSON object")
        return claims


def _split_and_header(token: str):
    """Shared strict structural parse: split, decode+check the header.

    Returns (header_dict, raw_header, raw_payload, raw_sig). Single
    source of truth for the structural rules — peek_alg, parse_compact,
    and the C++ runtime conformance tests all key off this behavior.
    """
    if not isinstance(token, str) or not token:
        raise MalformedTokenError("token is empty")
    parts = token.split(".")
    if len(parts) != 3:
        raise MalformedTokenError(
            f"compact JWS must have 3 segments, found {len(parts)}"
        )
    raw_header, raw_payload, raw_sig = parts
    header_bytes = b64url_decode(raw_header)
    try:
        header = json.loads(header_bytes)
    except (ValueError, UnicodeDecodeError) as e:
        raise MalformedTokenError(f"protected header is not valid JSON: {e}") from e
    if not isinstance(header, dict):
        raise MalformedTokenError("protected header is not a JSON object")
    alg = header.get("alg")
    if not isinstance(alg, str) or not alg:
        raise MalformedTokenError("protected header missing alg parameter")
    if "crit" in header:
        # go-jose rejects any JWS carrying a crit header, regardless of
        # its value; matching that keeps rejection parity with the
        # reference's verify path (jwt/keyset.go:155-167).
        raise MalformedTokenError("unsupported crit header")
    return header, raw_header, raw_payload, raw_sig


def peek_alg(token: str) -> str:
    """Return the alg header of a JWS, enforcing the same structural
    rules as :func:`parse_jws` but (for the compact form) without
    decoding the payload segment — cheap header-only inspection."""
    if is_json_form(token):
        return parse_json(token).alg
    header, _, raw_payload, raw_sig = _split_and_header(token)
    # Validate payload/signature segment charsets without decoding bytes.
    for seg in (raw_payload, raw_sig):
        if not set(seg) <= _B64URL_CHARS or len(seg) % 4 == 1:
            raise MalformedTokenError("illegal base64url segment")
    if not raw_sig:
        raise TokenNotSignedError("token must be signed")
    return header["alg"]


def parse_compact(token: str) -> ParsedJWS:
    """Parse a compact-serialization JWS without verifying it."""
    header, raw_header, raw_payload, raw_sig = _split_and_header(token)
    payload = b64url_decode(raw_payload)
    signature = b64url_decode(raw_sig)
    if len(signature) == 0:
        raise TokenNotSignedError("token must be signed")
    signing_input = (raw_header + "." + raw_payload).encode("ascii")
    return ParsedJWS(
        header=header,
        payload=payload,
        signature=signature,
        signing_input=signing_input,
    )


def is_json_form(token) -> bool:
    """True when the token uses the JWS JSON serialization (go-jose's
    detection rule: first non-whitespace byte is ``{``)."""
    return isinstance(token, str) and token.lstrip()[:1] == "{"


def _json_segment(obj, field: str, what: str) -> str:
    v = obj.get(field)
    if not isinstance(v, str):
        raise MalformedTokenError(f"JSON JWS {what} missing {field!r}")
    if not set(v) <= _B64URL_CHARS or len(v) % 4 == 1:
        raise MalformedTokenError("illegal base64url segment")
    return v


def _parse_json_signature(doc, sig_obj) -> ParsedJWS:
    """One signature object (+ shared payload) → ParsedJWS."""
    raw_payload = _json_segment(doc, "payload", "document")
    raw_header = _json_segment(sig_obj, "protected", "signature")
    raw_sig = _json_segment(sig_obj, "signature", "signature")

    header_bytes = b64url_decode(raw_header)
    try:
        header = json.loads(header_bytes)
    except (ValueError, UnicodeDecodeError) as e:
        raise MalformedTokenError(
            f"protected header is not valid JSON: {e}") from e
    if not isinstance(header, dict):
        raise MalformedTokenError("protected header is not a JSON object")

    unprotected = sig_obj.get("header")
    if unprotected is not None:
        if not isinstance(unprotected, dict):
            raise MalformedTokenError(
                "JSON JWS unprotected header is not a JSON object")
        # RFC 7515 §7.2.1: the two header sets MUST be disjoint.
        dup = set(header) & set(unprotected)
        if dup:
            raise MalformedTokenError(
                f"duplicate header parameter {sorted(dup)[0]!r}")
        merged = dict(unprotected)
        merged.update(header)
        header = merged

    alg = header.get("alg")
    if not isinstance(alg, str) or not alg:
        raise MalformedTokenError("protected header missing alg parameter")
    if "crit" in header:
        raise MalformedTokenError("unsupported crit header")

    payload = b64url_decode(raw_payload)
    signature = b64url_decode(raw_sig)
    if len(signature) == 0:
        raise TokenNotSignedError("token must be signed")
    return ParsedJWS(
        header=header,
        payload=payload,
        signature=signature,
        signing_input=(raw_header + "." + raw_payload).encode("ascii"),
    )


def parse_json(token: str) -> ParsedJWS:
    """Parse a JSON-serialization JWS (RFC 7515 §7.2) with exactly one
    signature — flattened or general form.

    The reference accepts this form through go-jose's ParseSigned and
    then enforces the single signature itself (jwt/jwt.go:212-227);
    more than one signature is rejected the same way here.
    """
    try:
        doc = json.loads(token)
    except (ValueError, UnicodeDecodeError) as e:
        raise MalformedTokenError(f"JSON JWS is not valid JSON: {e}") from e
    if not isinstance(doc, dict):
        raise MalformedTokenError("JSON JWS is not a JSON object")

    sigs = doc.get("signatures")
    if sigs is None:
        return _parse_json_signature(doc, doc)  # flattened form
    if not isinstance(sigs, list) or len(sigs) != 1:
        raise MalformedTokenError(
            "JSON JWS must carry exactly one signature")
    if "signature" in doc or "protected" in doc or "header" in doc:
        # RFC 7515 §7.2.1/§7.2.2: the general and flattened members are
        # mutually exclusive in one document.
        raise MalformedTokenError(
            "JSON JWS mixes general and flattened members")
    if not isinstance(sigs[0], dict):
        raise MalformedTokenError("JSON JWS signature is not an object")
    return _parse_json_signature(doc, sigs[0])


def parse_jws(token: str) -> ParsedJWS:
    """Parse a JWS in either serialization (go-jose ParseSigned's
    dispatch rule: a leading ``{`` means the JSON form)."""
    if is_json_form(token):
        return parse_json(token)
    return parse_compact(token)


def json_normalize(token: str):
    """Parse a JSON-form JWS; return ``(compact_or_None, parsed)``.

    ``compact`` preserves the signing input byte-for-byte (protected +
    "." + payload as they appear in the document), so signatures verify
    identically. ``compact`` is None — callers must verify via the
    returned ParsedJWS, whose merged header is authoritative — when
    the compact re-serialization would change the VERDICT, not just
    the bytes:

    - ``alg`` lives only in the unprotected header: the compact form
      would parse as alg-less and flip an accept into a reject;
    - ``kid`` lives only in the unprotected header: compacting drops
      it, so key selection would widen from "the kid-named key" to
      "every key of the alg's type" — a token whose unprotected kid
      names a different trusted key would then accept on the batch
      path while ``verify_signature`` (merged-header kid routing)
      rejects it.
    """
    parsed = parse_json(token)
    doc = json.loads(token)
    sig_obj = doc if doc.get("signatures") is None else doc["signatures"][0]
    protected = json.loads(b64url_decode(sig_obj["protected"]))
    if not isinstance(protected.get("alg"), str) or not protected["alg"]:
        return None, parsed
    unprotected = sig_obj.get("header")
    if isinstance(unprotected, dict) and "kid" in unprotected:
        return None, parsed
    return ".".join((sig_obj["protected"], doc["payload"],
                     sig_obj["signature"])), parsed


def json_to_compact(token: str) -> str:
    """Re-serialize a JSON-form JWS as the equivalent compact token.

    Raises for tokens whose compact form would verify differently
    (alg or kid present solely in the unprotected header) — batch
    machinery uses :func:`normalize_batch`, which falls back to
    object-path verification for those instead.
    """
    compact, _ = json_normalize(token)
    if compact is None:
        raise MalformedTokenError(
            "JSON JWS is not representable compactly without changing "
            "its verification semantics (alg or kid only in the "
            "unprotected header)")
    return compact


def normalize_batch(tokens):
    """Shared batch normalization: JSON-form entries → compact.

    Returns ``(tokens', specials)``. ``tokens'`` is ``tokens`` with
    every JSON-form entry replaced by its compact re-serialization
    (or ``""`` when it has none); ``specials`` maps those indices that
    can't ride the compact machinery to either the ParsedJWS to verify
    on the object path (valid but non-compactable) or the exact parse
    exception. The single source of truth for prep and the TPU batch
    dispatcher, so their error channels can never diverge.
    """
    out = None
    specials = {}
    for i, t in enumerate(tokens):
        if not is_json_form(t):
            continue
        if out is None:
            out = list(tokens)
        try:
            compact, parsed = json_normalize(t)
        except Exception as e:  # noqa: BLE001 - per-token error channel
            specials[i] = e
            out[i] = ""
            continue
        if compact is None:
            specials[i] = parsed
            out[i] = ""
        else:
            out[i] = compact
    return (tokens if out is None else out), specials
