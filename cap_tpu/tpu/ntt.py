"""Number-theoretic transform over Z_q, q = 8380417 (FIPS 204 §7.5).

The ML-DSA verify hot loop is NTT-dominated (PAPERS.md: the GPU
Dilithium engine spends ~70% of verify in NTT + pointwise ring mults),
and the 256-point transform over the Dilithium prime maps exactly onto
the repo's batch-lane shape: one token = a handful of degree-255
polynomials, a batch = a [B, ·, 256] integer lane array, and every
butterfly stage is one vectorized multiply-add sweep across all lanes
at once.

Arithmetic strategy (TPU-safe):

- coefficients ride in **uint32 lanes** in canonical form [0, q);
- products use **Montgomery reduction with R = 2^32**, built from
  16-bit limb multiplies so nothing ever needs an int64 (TPUs have no
  64-bit integer units; XLA:CPU lowers the same graph to scalar ops);
- the twiddle tables are stored in Montgomery form (ζ·R mod q), so
  ``mont_mul(zeta_mont, x)`` yields the PLAIN product ζ·x mod q —
  data stays in the plain domain through the whole transform and no
  global domain conversion is ever needed. Pointwise key-table mults
  use the same trick: tables are uploaded in Montgomery form once
  (key material is long-lived), per-token data stays plain;
- the inverse transform folds the 256⁻¹ scaling into one final
  Montgomery multiply by (256⁻¹·R mod q).

The stage loops are unrolled host-side (8 fixed stages), each stage a
reshape + one batched butterfly over [..., blocks, 2, len] — XLA sees
a short static program per batch shape, the same compile-once shape
discipline as the RSA/EC engines.

``ntt_ref``/``intt_ref`` are the numpy int64 host references (exact
integer arithmetic, no Montgomery) — the pure-int oracle in
``mldsa.py`` runs on them, and the parity tests pin the uint32 device
graph against them butterfly-for-butterfly.
"""

from __future__ import annotations

import numpy as np

# jax is imported INSIDE the device kernels: the numpy references and
# the twiddle tables at the bottom serve the pure-int host oracle in
# ``mldsa.py``, which must stay importable (and cheap) on hosts that
# never touch the accelerator — the same lazy-jax stance as the jwt
# package's lazy TPUBatchKeySet export.

Q = 8380417                       # 2^23 - 2^13 + 1
N = 256
ZETA = 1753                       # primitive 512th root of unity mod q
MONT_BITS = 32
MONT_R = (1 << MONT_BITS) % Q
# -q^{-1} mod 2^32 for unsigned REDC: p + (p·NQINV mod 2^32)·q ≡ 0 (mod 2^32)
NQINV = (-pow(Q, -1, 1 << MONT_BITS)) % (1 << MONT_BITS)
INV256 = pow(N, -1, Q)


def _bitrev8(x: int) -> int:
    r = 0
    for _ in range(8):
        r = (r << 1) | (x & 1)
        x >>= 1
    return r


# zetas[k] = ζ^bitrev8(k) mod q, consumed in index order by the
# standard Cooley-Tukey schedule (zetas[0] is never referenced).
ZETAS = np.array([pow(ZETA, _bitrev8(k), Q) for k in range(N)], np.int64)
ZETAS_MONT = ((ZETAS << MONT_BITS) % Q).astype(np.uint32)
NEG_ZETAS_MONT = (((Q - ZETAS) << MONT_BITS) % Q).astype(np.uint32)
INV256_MONT = np.uint32((INV256 << MONT_BITS) % Q)

_Q32 = np.uint32(Q)
_NQINV32 = np.uint32(NQINV)
_MASK16 = np.uint32(0xFFFF)


# ---------------------------------------------------------------------------
# uint32 Montgomery arithmetic (no 64-bit integers anywhere)
# ---------------------------------------------------------------------------

def _mulhi32(a, b):
    """High 32 bits of the 64-bit product of two uint32 arrays,
    computed from 16-bit limbs (every partial product stays < 2^32)."""
    a0 = a & _MASK16
    a1 = a >> 16
    b0 = b & _MASK16
    b1 = b >> 16
    t = a0 * b0
    u = a1 * b0 + (t >> 16)           # ≤ (2^16-1)^2 + (2^16-1) < 2^32
    v = a0 * b1 + (u & _MASK16)
    return a1 * b1 + (u >> 16) + (v >> 16)


def mont_mul(a, b):
    """Montgomery product a·b·R⁻¹ mod q for uint32 lanes in [0, q).

    With one operand pre-multiplied by R (twiddles, key tables) this
    is the PLAIN modular product of the other operand — the only way
    the engine ever multiplies. Result is canonical [0, q).
    """
    import jax.numpy as jnp

    lo = a * b                        # wraps mod 2^32 (uint32 lanes)
    hi = _mulhi32(a, b)
    m = lo * _NQINV32                 # mod 2^32
    mq_hi = _mulhi32(m, _Q32)
    # lo + low32(m·q) ≡ 0 (mod 2^32): the carry out is 1 iff lo != 0.
    t = hi + mq_hi + (lo != 0).astype(jnp.uint32)
    return jnp.where(t >= _Q32, t - _Q32, t)


def add_q(a, b):
    import jax.numpy as jnp

    t = a + b
    return jnp.where(t >= _Q32, t - _Q32, t)


def sub_q(a, b):
    import jax.numpy as jnp

    return jnp.where(a >= b, a - b, a + _Q32 - b)


# ---------------------------------------------------------------------------
# batched NTT / inverse NTT (last axis = 256 coefficients)
# ---------------------------------------------------------------------------

def ntt(x):
    """Forward NTT, plain domain in → plain domain out (CRYSTALS
    bit-reversed frequency order). x: uint32 [..., 256] in [0, q).

    Dispatches to the FUSED Pallas kernel (``pallas_ntt.ntt_fused``,
    all 8 stages on one VMEM tile) when that path is enabled; the
    stagewise jnp graph below is the CPU/XLA fallback and the parity
    reference — bit-identical either way (tests/test_pallas_ntt.py).
    """
    import jax.numpy as jnp

    from . import pallas_ntt

    if pallas_ntt.enabled():
        return pallas_ntt.ntt_fused(x)
    shape = x.shape
    lead = shape[:-1]
    for s in range(8):                # len = 128 >> s
        ln = 128 >> s
        nblk = N // (2 * ln)
        z = jnp.asarray(ZETAS_MONT[nblk: 2 * nblk])       # [nblk]
        v = x.reshape(lead + (nblk, 2, ln))
        lo_, hi_ = v[..., 0, :], v[..., 1, :]
        t = mont_mul(z[..., :, None], hi_)
        x = jnp.stack([add_q(lo_, t), sub_q(lo_, t)],
                      axis=-2).reshape(shape)
    return x


def intt(x):
    """Inverse NTT (Gentleman-Sande), including the 256⁻¹ scaling.
    Plain domain in/out; exact inverse of :func:`ntt`. Same fused-
    kernel dispatch as :func:`ntt`."""
    import jax.numpy as jnp

    from . import pallas_ntt

    if pallas_ntt.enabled():
        return pallas_ntt.intt_fused(x)
    shape = x.shape
    lead = shape[:-1]
    for s in range(8):                # len = 1 << s
        ln = 1 << s
        nblk = N // (2 * ln)
        # k decrements from 2·nblk-1 down to nblk as blocks advance.
        z = jnp.asarray(NEG_ZETAS_MONT[nblk: 2 * nblk][::-1].copy())
        v = x.reshape(lead + (nblk, 2, ln))
        lo_, hi_ = v[..., 0, :], v[..., 1, :]
        t = lo_
        lo_ = add_q(t, hi_)
        hi_ = mont_mul(z[..., :, None], sub_q(t, hi_))
        x = jnp.stack([lo_, hi_], axis=-2).reshape(shape)
    return mont_mul(jnp.asarray(INV256_MONT), x)


# ---------------------------------------------------------------------------
# Decompose / UseHint lanes (FIPS 204 §7.4) — per-parameter-set γ2
# ---------------------------------------------------------------------------

def use_hint(h, r, gamma2: int):
    """Vectorized UseHint: w1 lanes from hint bits + raw w lanes.

    h: uint32/uint8 [..., 256] in {0,1}; r: uint32 [..., 256] in
    [0, q); gamma2: 95232 (ML-DSA-44) or 261888 (65/87), a static
    Python int so each parameter set compiles its own graph.
    Returns uint32 w1 in [0, m) with m = (q-1)/(2γ2).
    """
    import jax.numpy as jnp

    two_g2 = np.uint32(2 * gamma2)
    g2 = np.uint32(gamma2)
    m = np.uint32((Q - 1) // (2 * gamma2))
    rm = r % two_g2
    is_neg = rm > g2                  # centered r0 < 0
    r_sub_r0 = r - rm + jnp.where(is_neg, two_g2, np.uint32(0))
    special = r_sub_r0 == np.uint32(Q - 1)    # r1 wraps to 0, r0 -= 1
    r1 = jnp.where(special, np.uint32(0), r_sub_r0 // two_g2)
    r0_pos = (~special) & (~is_neg) & (rm > 0)
    h = h.astype(jnp.uint32)
    bumped = jnp.where(r0_pos, r1 + np.uint32(1), r1 + m - np.uint32(1)) % m
    return jnp.where(h != 0, bumped, r1)


# ---------------------------------------------------------------------------
# numpy int64 host reference (exact arithmetic; the oracle's transform)
# ---------------------------------------------------------------------------

def ntt_ref(x: np.ndarray) -> np.ndarray:
    """Forward NTT on int64 numpy lanes [..., 256], values [0, q)."""
    a = np.asarray(x, np.int64).copy()
    k = 0
    ln = 128
    while ln >= 1:
        for start in range(0, N, 2 * ln):
            k += 1
            z = int(ZETAS[k])
            t = (z * a[..., start + ln: start + 2 * ln]) % Q
            a[..., start + ln: start + 2 * ln] = \
                (a[..., start: start + ln] - t) % Q
            a[..., start: start + ln] = \
                (a[..., start: start + ln] + t) % Q
        ln //= 2
    return a


def intt_ref(x: np.ndarray) -> np.ndarray:
    """Inverse NTT on int64 numpy lanes; exact inverse of ntt_ref."""
    a = np.asarray(x, np.int64).copy()
    k = N
    ln = 1
    while ln < N:
        for start in range(0, N, 2 * ln):
            k -= 1
            z = Q - int(ZETAS[k])
            t = a[..., start: start + ln].copy()
            a[..., start: start + ln] = \
                (t + a[..., start + ln: start + 2 * ln]) % Q
            a[..., start + ln: start + 2 * ln] = \
                (z * (t - a[..., start + ln: start + 2 * ln])) % Q
        ln *= 2
    return (a * INV256) % Q


def use_hint_ref(h: np.ndarray, r: np.ndarray, gamma2: int) -> np.ndarray:
    """numpy reference of :func:`use_hint` (same special-case rules)."""
    r = np.asarray(r, np.int64)
    two_g2 = 2 * gamma2
    m = (Q - 1) // two_g2
    rm = r % two_g2
    is_neg = rm > gamma2
    r_sub_r0 = r - rm + np.where(is_neg, two_g2, 0)
    special = r_sub_r0 == Q - 1
    r1 = np.where(special, 0, r_sub_r0 // two_g2)
    r0_pos = (~special) & (~is_neg) & (rm > 0)
    bumped = np.where(r0_pos, r1 + 1, r1 + m - 1) % m
    return np.where(np.asarray(h) != 0, bumped, r1)
