"""Batched big-number arithmetic as JAX/XLA programs.

Replaces the modular-exponentiation inner loop of the reference's verify
path (Go crypto/rsa via go-jose, jwt/keyset.go:126-139) with TPU-shaped
arithmetic:

- numbers are little-endian base-2^16 limb vectors, limb-first [K, N]
  (batch N rides the 128-wide vector lanes; the TPU has no 64-bit
  scalar multiplier, so limbs are sized such that a limb product fits
  exactly in uint32 and column sums of split hi/lo parts stay < 2^25);
- multiplication is a fully-vectorized convolution: the [K, K, N]
  partial-product tensor is skew-reshaped so anti-diagonals become
  columns, and one reduction produces all 2K-1 output columns — no
  sequential limb loop, no dynamic slices (for large K the j-axis is
  blocked to bound the materialized tensor);
- carries/borrows resolve in FIXED depth: one ripple pass brings
  pending carries to {0,1}, then a Kogge-Stone-style carry-lookahead
  over the limb axis (``lax.associative_scan``, log₂K steps) delivers
  exact propagation even for adversarial all-0xFFFF ripple chains —
  there is no data-dependent ``while_loop`` anywhere, so XLA sees one
  static dataflow graph per bucket;
- separated Montgomery multiplication: T = a·b, m = T·N' mod R,
  t = (T + m·n)/R, one conditional subtract — all batched, with
  per-token moduli (gathered from a device-resident JWKS key table);
- modexp: fast path for e = 65537 (16 squarings + 1 multiply), generic
  left-to-right ladder for arbitrary per-token exponents.

Everything here is shape-static and branchless (lax control flow only),
so one XLA compilation serves a whole bucket of same-shape tokens.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .limbs import LIMB_BITS, LIMB_MASK

U32 = jnp.uint32
I32 = jnp.int32


def _shift_up(x: jnp.ndarray) -> jnp.ndarray:
    """Shift one limb toward the most-significant end (row 0 ← zero)."""
    return jnp.concatenate([jnp.zeros_like(x[:1]), x[:-1]], axis=0)


def _carry_lookahead(digits: jnp.ndarray, carry_in: jnp.ndarray,
                     propagate_at: int) -> jnp.ndarray:
    """Exact resolution of unit carries/borrows in log₂K steps.

    digits: [K, N] values in [0, 2^16); carry_in: [K, N] {0,1} unit
    carries arriving AT each limb; propagate_at: the digit value that
    forwards an incoming carry (0xFFFF for carries, 0 for borrows).
    Returns u [K, N]: the total unit adjustment arriving at each limb,
    u_i = carry_in_i | (prop_{i-1} & u_{i-1}) — a Kogge-Stone-style
    prefix over the limb axis via ``lax.associative_scan``.
    """
    prop_below = _shift_up(digits == propagate_at)

    def combine(left, right):
        gl, ql = left
        gr, qr = right
        return gr | (qr & gl), ql & qr

    u, _ = lax.associative_scan(
        combine, (carry_in.astype(bool), prop_below), axis=0)
    return u.astype(U32)


def carry_normalize(v: jnp.ndarray) -> jnp.ndarray:
    """Propagate carries until every limb is < 2^16 (exact, fixed depth).

    v: [K, N] uint32 with limbs possibly up to 2^32-1. The top limb must
    have headroom for the final carry (callers allocate a spare limb).
    One ripple pass reduces pending carries to {0,1}; a carry-lookahead
    scan resolves them exactly — adversarial all-0xFFFF ripple chains
    included — with no data-dependent control flow.
    """
    # Pass 1: any u32 digit < 2^32 → digit < 2^17, carry ≤ 2^16.
    v1 = (v & LIMB_MASK) + _shift_up(v >> LIMB_BITS)
    # Pass 2 split: digits < 2^16, unit carries ∈ {0,1}.
    l2 = v1 & LIMB_MASK
    c2 = _shift_up(v1 >> LIMB_BITS)
    u = _carry_lookahead(l2, c2, LIMB_MASK)
    # l2 + u ≤ 2^16; the == 2^16 case masks to 0 with its carry already
    # delivered to the limb above by the lookahead.
    return (l2 + u) & LIMB_MASK


def _anti_diag_tree(rows: jnp.ndarray) -> jnp.ndarray:
    """Sum rows of a [J, W, N] tensor where row j sits at limb offset j.

    Pairwise log-tree: at level l paired rows differ by a 2^l-limb
    offset, so each merge is a static pad + add (no reshapes, no
    gathers — everything fuses). Returns [W + J - 1, N].
    """
    stride = 1
    while rows.shape[0] > 1:
        if rows.shape[0] % 2:
            rows = jnp.pad(rows, ((0, 1), (0, 0), (0, 0)))
        even = jnp.pad(rows[0::2], ((0, 0), (0, stride), (0, 0)))
        odd = jnp.pad(rows[1::2], ((0, 0), (stride, 0), (0, 0)))
        rows = even + odd
        stride *= 2
    return rows[0]


_MUL_BLOCK_J = 64  # bounds the [Bj, K+1, N] partial-product tensor


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Full product of two [K, N] limb arrays → [2K+1, N] normalized.

    Vectorized convolution: the partial-product tensor b_j·a_i is
    split into 16-bit hi/lo halves (exact in u32), folded into a
    [Bj, K+1, N] row tensor per j-block (blocking bounds the
    materialized tensor for RSA-sized K), and anti-diagonal-summed by
    the static pad/add log-tree. Column sums stay exact: ≤ 2K terms
    < 2^16 each → < 2^25 for K ≤ 256 (RSA-4096).
    """
    k, n = a.shape
    if k <= _MUL_BLOCK_J:
        p = b[:, None, :] * a[None, :, :]                 # [K, K, N]
        rows = (jnp.pad(p & LIMB_MASK, ((0, 0), (0, 1), (0, 0)))
                + jnp.pad(p >> LIMB_BITS, ((0, 0), (1, 0), (0, 0))))
        c = _anti_diag_tree(rows)[: 2 * k]   # tail beyond 2K is zero
        return carry_normalize(jnp.pad(c, ((0, 1), (0, 0))))

    acc = jnp.zeros((2 * k + 1, n), dtype=U32)
    for j0 in range(0, k, _MUL_BLOCK_J):
        bj = min(_MUL_BLOCK_J, k - j0)
        p = b[j0:j0 + bj, None, :] * a[None, :, :]        # [Bj, K, N]
        rows = (jnp.pad(p & LIMB_MASK, ((0, 0), (0, 1), (0, 0)))
                + jnp.pad(p >> LIMB_BITS, ((0, 0), (1, 0), (0, 0))))
        c = _anti_diag_tree(rows)[: k + bj]  # offsets j0 .. j0+k+bj-1
        acc = acc.at[j0: j0 + k + bj].add(c)
    return carry_normalize(acc)


def compare_ge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic a >= b over normalized [K, N] limb arrays → [N] bool."""
    gt = a > b
    lt = a < b
    # Most-significant differing limb decides: scan from the top.
    # higher_eq[i] = all limbs above i are equal.
    eq = a == b
    higher_eq = jnp.flip(jnp.cumprod(jnp.flip(eq, 0).astype(U32), axis=0), 0)
    higher_eq = jnp.concatenate(
        [higher_eq[1:], jnp.ones_like(higher_eq[:1])], axis=0
    ).astype(bool)
    decides_gt = jnp.any(gt & higher_eq, axis=0)
    decides_lt = jnp.any(lt & higher_eq, axis=0)
    return decides_gt | ~(decides_gt | decides_lt)


def sub_where(a: jnp.ndarray, b: jnp.ndarray,
              mask: jnp.ndarray) -> jnp.ndarray:
    """Where mask: a - b (requires a >= b there); else a. [K, N] inputs.

    Normalized (< 2^16-digit) inputs; exact fixed-depth borrow
    resolution via the same lookahead scan as ``carry_normalize``
    (a zero digit propagates an incoming borrow).
    """
    d = a.astype(I32) - jnp.where(mask[None, :], b, 0).astype(I32)
    lo = (d & LIMB_MASK).astype(U32)            # d mod 2^16, two's compl.
    borrow = _shift_up((d < 0).astype(U32))     # unit borrows arriving AT i
    u = _carry_lookahead(lo, borrow, 0)
    return (lo.astype(I32) - u.astype(I32)).astype(U32) & LIMB_MASK


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, n: jnp.ndarray,
             nprime: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product a·b·R⁻¹ mod n, R = 2^(16K).

    a, b, n: [K, N] normalized, a·b < R·n. nprime: [K, N] limbs of
    N' = -n⁻¹ mod R (per-token, gathered from the key table).
    Separated form: T = a·b; m = (T mod R)·N' mod R; t = (T + m·n)/R;
    conditional subtract brings t < n.
    """
    k, _ = a.shape
    t_full = mul(a, b)                       # [2K+1, N]
    t_low = t_full[:k]
    m = mul(t_low, nprime)[:k]               # low K limbs ≡ mod R
    mn = mul(m, n)                           # [2K+1, N]
    # T + m·n: both normalized, sums < 2^17 → one spare limb suffices.
    s = carry_normalize(t_full + mn)         # low K limbs are exactly 0
    t = s[k:]                                # [K+1, N]; value < 2n
    n_pad = jnp.concatenate([n, jnp.zeros_like(n[:1])], axis=0)
    ge = compare_ge(t, n_pad)
    return sub_where(t, n_pad, ge)[:k]


def mont_sqr(a, n, nprime):
    return mont_mul(a, a, n, nprime)


def mont_mul_lazy(a: jnp.ndarray, b: jnp.ndarray, n: jnp.ndarray,
                  nprime: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product WITHOUT the conditional subtract.

    Requires R = 2^(16K) ≥ 4n (callers allocate one spare limb beyond
    the modulus width). For inputs < 2n (as values; canonical digits),
    the output t = (ab + mn)/R < 2n — so a whole modexp chain runs with
    no compares/subtractions at all, and one final reduction
    canonicalizes. Classic subtraction-free Montgomery.
    """
    k = a.shape[0]
    t_full = mul(a, b)                       # [2K+1, N]
    m = mul(t_full[:k], nprime)[:k]
    mn = mul(m, n)
    s = carry_normalize(t_full + mn)         # low K limbs exactly 0
    return s[k: 2 * k]


@partial(jax.jit, static_argnames=("to_mont",))
def modexp_65537(s: jnp.ndarray, n: jnp.ndarray, nprime: jnp.ndarray,
                 r2: jnp.ndarray, to_mont: bool = True) -> jnp.ndarray:
    """s^65537 mod n for the whole batch (the RSA fast path).

    s, n, nprime, r2: [K, N]; r2 = R² mod n per token; R ≥ 4n (the key
    table allocates the spare limb). 19 subtraction-free Montgomery
    multiplies (domain entry, 16 unrolled squarings, ·s, domain exit),
    then ONE canonicalizing conditional subtract.
    """
    s_m = mont_mul_lazy(s, r2, n, nprime) if to_mont else s
    x = s_m
    for _ in range(16):                      # static unroll: one graph
        x = mont_mul_lazy(x, x, n, nprime)
    x = mont_mul_lazy(x, s_m, n, nprime)
    one = jnp.zeros_like(s).at[0].set(1)
    x = mont_mul_lazy(x, one, n, nprime)     # leave domain; x ≤ n
    return sub_where(x, n, compare_ge(x, n))


@partial(jax.jit, static_argnames=("ebits",))
def modexp_vare(s: jnp.ndarray, e: jnp.ndarray, n: jnp.ndarray,
                nprime: jnp.ndarray, r2: jnp.ndarray, one_mont: jnp.ndarray,
                ebits: int) -> jnp.ndarray:
    """s^e mod n with per-token 32-bit exponents (general RSA keys).

    e: [N] uint32. ebits is the static max bit-length in the bucket.
    Left-to-right ladder with per-token bit selects (branchless).
    """
    s_m = mont_mul(s, r2, n, nprime)

    def body(i, x):
        bit_idx = ebits - 1 - i
        x = mont_sqr(x, n, nprime)
        mult = mont_mul(x, s_m, n, nprime)
        bit = (e >> bit_idx) & 1
        return jnp.where(bit[None, :].astype(bool), mult, x)

    x = lax.fori_loop(0, ebits, body, one_mont)
    one = jnp.zeros_like(s).at[0].set(1)
    return mont_mul(x, one, n, nprime)


@partial(jax.jit, static_argnames=("ebits", "exit_domain", "s_in_mont"))
def modexp_fixed_exponent(s: jnp.ndarray, e_limbs: jnp.ndarray,
                          n: jnp.ndarray, nprime: jnp.ndarray,
                          r2: jnp.ndarray, one_mont: jnp.ndarray,
                          ebits: int, exit_domain: bool = True,
                          s_in_mont: bool = False) -> jnp.ndarray:
    """s^E mod n for big per-token exponents E given as [KE, N] limbs.

    Used by the EC layer for Fermat inversions (E = n-2, broadcast) and
    any path that needs a full-width exponent. ebits = static exponent
    bit-width. Branchless left-to-right ladder over all ebits bits.
    exit_domain=False returns the result in Montgomery form (the EC
    scalar path multiplies it straight into other Montgomery values);
    s_in_mont=True skips the domain entry for an already-Montgomery s.
    """
    s_m = s if s_in_mont else mont_mul(s, r2, n, nprime)

    def body(i, x):
        bit_idx = ebits - 1 - i
        limb = bit_idx // LIMB_BITS
        shift = bit_idx % LIMB_BITS
        bit = (e_limbs[limb] >> shift) & 1
        x = mont_sqr(x, n, nprime)
        mult = mont_mul(x, s_m, n, nprime)
        return jnp.where(bit[None, :].astype(bool), mult, x)

    x = lax.fori_loop(0, ebits, body, one_mont)
    if not exit_domain:
        return x
    one = jnp.zeros_like(s).at[0].set(1)
    return mont_mul(x, one, n, nprime)


def batch_mont_inverse(x_m: jnp.ndarray, n1: jnp.ndarray, npp1: jnp.ndarray,
                       nr2_1: jnp.ndarray, none1: jnp.ndarray,
                       nm2_1: jnp.ndarray, nbits: int,
                       min_width: int = 128) -> jnp.ndarray:
    """Simultaneous inversion of a whole batch (Montgomery domain).

    Montgomery's product-tree trick: pair-multiply up to a ``min_width``
    root, invert the root with ONE Fermat ladder, then walk back down —
    ~3 multiplies per element instead of a 2·nbits-multiply ladder per
    element. Replaces the dominant per-token s⁻¹ cost of ECDSA verify
    (the reference's crypto/ecdsa.Verify inverts per call).

    x_m: [K, N] nonzero values in Montgomery form, N a power of two.
    n1/npp1/nr2_1/none1/nm2_1: [K, 1] broadcastable modulus constants
    (modulus, N', R², R mod n, and the Fermat exponent n−2).
    Returns [K, N]: per-element inverses, Montgomery form.
    """
    k, n_batch = x_m.shape

    def bc(c, width):
        return jnp.broadcast_to(c, (k, width))

    levels = [x_m]
    cur = x_m
    while cur.shape[1] > min_width and cur.shape[1] % 2 == 0:
        half = cur.shape[1] // 2
        cur = mont_mul(cur[:, 0::2], cur[:, 1::2], bc(n1, half),
                       bc(npp1, half))
        levels.append(cur)

    w = cur.shape[1]
    root_inv = modexp_fixed_exponent(
        cur, bc(nm2_1, w), bc(n1, w), bc(npp1, w), bc(nr2_1, w),
        bc(none1, w), ebits=nbits, exit_domain=False, s_in_mont=True)

    inv = root_inv
    for lvl in levels[-2::-1]:
        width = lvl.shape[1]
        half = width // 2
        left = lvl[:, 0::2]
        right = lvl[:, 1::2]
        nh, nph = bc(n1, half), bc(npp1, half)
        inv_left = mont_mul(inv, right, nh, nph)
        inv_right = mont_mul(inv, left, nh, nph)
        inv = jnp.stack([inv_left, inv_right], axis=2).reshape(k, width)
    return inv


# ---------------------------------------------------------------------------
# Modular add/sub (used by the EC layer; operands already reduced < m)
# ---------------------------------------------------------------------------

def add_mod(a: jnp.ndarray, b: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """(a + b) mod m over [K, N] normalized limb arrays, a, b < m."""
    k = a.shape[0]
    zero_row = jnp.zeros_like(a[:1])
    t = carry_normalize(jnp.concatenate([a + b, zero_row], axis=0))
    m_pad = jnp.concatenate([m, zero_row], axis=0)
    ge = compare_ge(t, m_pad)
    return sub_where(t, m_pad, ge)[:k]


def sub_mod(a: jnp.ndarray, b: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """(a - b) mod m over [K, N] normalized limb arrays, a, b < m."""
    k = a.shape[0]
    zero_row = jnp.zeros_like(a[:1])
    # a + m - b: always non-negative, < 2m.
    t = carry_normalize(jnp.concatenate([a + m, zero_row], axis=0))
    b_pad = jnp.concatenate([b, zero_row], axis=0)
    t = sub_where(t, b_pad, jnp.ones(a.shape[1], dtype=bool))
    m_pad = jnp.concatenate([m, zero_row], axis=0)
    ge = compare_ge(t, m_pad)
    return sub_where(t, m_pad, ge)[:k]


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """[K, N] normalized limbs → [N] bool: value == 0."""
    return jnp.all(a == 0, axis=0)


# ---------------------------------------------------------------------------
# Host-side Montgomery precomputation (per key; plain Python ints)
# ---------------------------------------------------------------------------

def mont_params(n_int: int, k: int):
    """Montgomery constants for modulus n with R = 2^(16k).

    Returns (nprime_int, r2_int, one_mont_int):
    N' = -n⁻¹ mod R;  R² mod n;  R mod n.
    """
    if n_int % 2 == 0:
        raise ValueError("modulus must be odd")
    r = 1 << (LIMB_BITS * k)
    if n_int >= r:
        raise ValueError("modulus does not fit in k limbs")
    n_inv = pow(n_int, -1, r)
    nprime = (-n_inv) % r
    return nprime, (r * r) % n_int, r % n_int
