"""Batched big-number arithmetic as JAX/XLA programs.

Replaces the modular-exponentiation inner loop of the reference's verify
path (Go crypto/rsa via go-jose, jwt/keyset.go:126-139) with TPU-shaped
arithmetic:

- numbers are little-endian base-2^16 limb vectors, limb-first [K, N]
  (batch N rides the 128-wide vector lanes; the TPU has no 64-bit
  scalar multiplier, so limbs are sized such that a limb product fits
  exactly in uint32 and column sums of split hi/lo parts stay < 2^25);
- schoolbook convolution with split hi/lo accumulation (exact in
  uint32), carry normalization via `lax.while_loop` (data-dependent
  ripple depth, almost always 2-3 passes);
- separated Montgomery multiplication: T = a·b, m = T·N' mod R,
  t = (T + m·n)/R, one conditional subtract — all batched, with
  per-token moduli (gathered from a device-resident JWKS key table);
- modexp: fast path for e = 65537 (16 squarings + 1 multiply), generic
  left-to-right ladder for arbitrary per-token exponents.

Everything here is shape-static and branchless (lax control flow only),
so one XLA compilation serves a whole bucket of same-shape tokens.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .limbs import LIMB_BITS, LIMB_MASK

U32 = jnp.uint32
I32 = jnp.int32


def carry_normalize(v: jnp.ndarray) -> jnp.ndarray:
    """Propagate carries until every limb is < 2^16.

    v: [K, N] uint32 with limbs possibly up to 2^32-1. The top limb must
    have headroom for the final carry (callers allocate a spare limb).
    Runs a vectorized ripple pass under while_loop; random data converges
    in 2 passes, adversarial all-0xFFFF patterns take up to K.
    """

    def cond(x):
        return jnp.any(x > LIMB_MASK)

    def body(x):
        carries = x >> LIMB_BITS
        shifted = jnp.concatenate(
            [jnp.zeros_like(carries[:1]), carries[:-1]], axis=0
        )
        return (x & LIMB_MASK) + shifted

    return lax.while_loop(cond, body, v)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Full product of two [K, N] limb arrays → [2K+1, N] normalized.

    Schoolbook convolution: for each limb j of b, add a·b_j into the
    accumulator at offset j, with each 32-bit partial product split into
    16-bit hi/lo halves so column sums stay exact in uint32
    (≤ 2K terms < 2^16 each → < 2^25 for K ≤ 256, i.e. RSA-4096).
    """
    k, n = a.shape
    acc = jnp.zeros((2 * k + 1, n), dtype=U32)

    def body(j, acc):
        bj = lax.dynamic_slice_in_dim(b, j, 1, axis=0)  # [1, N]
        p = a * bj                                       # exact in uint32
        zero_row = jnp.zeros((1, n), dtype=U32)
        lo = jnp.concatenate([p & LIMB_MASK, zero_row], axis=0)   # [K+1, N]
        hi = jnp.concatenate([zero_row, p >> LIMB_BITS], axis=0)  # [K+1, N]
        window = lax.dynamic_slice_in_dim(acc, j, k + 1, axis=0)
        return lax.dynamic_update_slice_in_dim(
            acc, window + lo + hi, j, axis=0
        )

    acc = lax.fori_loop(0, k, body, acc)
    return carry_normalize(acc)


def compare_ge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic a >= b over normalized [K, N] limb arrays → [N] bool."""
    gt = a > b
    lt = a < b
    # Most-significant differing limb decides: scan from the top.
    # higher_eq[i] = all limbs above i are equal.
    eq = a == b
    higher_eq = jnp.flip(jnp.cumprod(jnp.flip(eq, 0).astype(U32), axis=0), 0)
    higher_eq = jnp.concatenate(
        [higher_eq[1:], jnp.ones_like(higher_eq[:1])], axis=0
    ).astype(bool)
    decides_gt = jnp.any(gt & higher_eq, axis=0)
    decides_lt = jnp.any(lt & higher_eq, axis=0)
    return decides_gt | ~(decides_gt | decides_lt)


def sub_where(a: jnp.ndarray, b: jnp.ndarray,
              mask: jnp.ndarray) -> jnp.ndarray:
    """Where mask: a - b (requires a >= b there); else a. [K, N] inputs."""
    d = a.astype(I32) - jnp.where(mask[None, :], b, 0).astype(I32)

    def cond(x):
        return jnp.any(x < 0)

    def body(x):
        borrow = (x < 0).astype(I32)
        repaid = x + borrow * (LIMB_MASK + 1)
        shifted = jnp.concatenate(
            [jnp.zeros_like(borrow[:1]), borrow[:-1]], axis=0
        )
        return repaid - shifted

    return lax.while_loop(cond, body, d).astype(U32)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, n: jnp.ndarray,
             nprime: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product a·b·R⁻¹ mod n, R = 2^(16K).

    a, b, n: [K, N] normalized, a·b < R·n. nprime: [K, N] limbs of
    N' = -n⁻¹ mod R (per-token, gathered from the key table).
    Separated form: T = a·b; m = (T mod R)·N' mod R; t = (T + m·n)/R;
    conditional subtract brings t < n.
    """
    k, _ = a.shape
    t_full = mul(a, b)                       # [2K+1, N]
    t_low = t_full[:k]
    m = mul(t_low, nprime)[:k]               # low K limbs ≡ mod R
    mn = mul(m, n)                           # [2K+1, N]
    # T + m·n: both normalized, sums < 2^17 → one spare limb suffices.
    s = carry_normalize(t_full + mn)         # low K limbs are exactly 0
    t = s[k:]                                # [K+1, N]; value < 2n
    n_pad = jnp.concatenate([n, jnp.zeros_like(n[:1])], axis=0)
    ge = compare_ge(t, n_pad)
    return sub_where(t, n_pad, ge)[:k]


def mont_sqr(a, n, nprime):
    return mont_mul(a, a, n, nprime)


@partial(jax.jit, static_argnames=("to_mont",))
def modexp_65537(s: jnp.ndarray, n: jnp.ndarray, nprime: jnp.ndarray,
                 r2: jnp.ndarray, to_mont: bool = True) -> jnp.ndarray:
    """s^65537 mod n for the whole batch (the RSA fast path).

    s, n, nprime, r2: [K, N]; r2 = R² mod n per token. 19 Montgomery
    multiplies: domain entry, 16 squarings, ·s, domain exit.
    """
    s_m = mont_mul(s, r2, n, nprime) if to_mont else s
    x = s_m

    def body(_, x):
        return mont_sqr(x, n, nprime)

    x = lax.fori_loop(0, 16, body, x)
    x = mont_mul(x, s_m, n, nprime)
    one = jnp.zeros_like(s).at[0].set(1)
    return mont_mul(x, one, n, nprime)       # leave Montgomery domain


@partial(jax.jit, static_argnames=("ebits",))
def modexp_vare(s: jnp.ndarray, e: jnp.ndarray, n: jnp.ndarray,
                nprime: jnp.ndarray, r2: jnp.ndarray, one_mont: jnp.ndarray,
                ebits: int) -> jnp.ndarray:
    """s^e mod n with per-token 32-bit exponents (general RSA keys).

    e: [N] uint32. ebits is the static max bit-length in the bucket.
    Left-to-right ladder with per-token bit selects (branchless).
    """
    s_m = mont_mul(s, r2, n, nprime)

    def body(i, x):
        bit_idx = ebits - 1 - i
        x = mont_sqr(x, n, nprime)
        mult = mont_mul(x, s_m, n, nprime)
        bit = (e >> bit_idx) & 1
        return jnp.where(bit[None, :].astype(bool), mult, x)

    x = lax.fori_loop(0, ebits, body, one_mont)
    one = jnp.zeros_like(s).at[0].set(1)
    return mont_mul(x, one, n, nprime)


@partial(jax.jit, static_argnames=("ebits", "exit_domain", "s_in_mont"))
def modexp_fixed_exponent(s: jnp.ndarray, e_limbs: jnp.ndarray,
                          n: jnp.ndarray, nprime: jnp.ndarray,
                          r2: jnp.ndarray, one_mont: jnp.ndarray,
                          ebits: int, exit_domain: bool = True,
                          s_in_mont: bool = False) -> jnp.ndarray:
    """s^E mod n for big per-token exponents E given as [KE, N] limbs.

    Used by the EC layer for Fermat inversions (E = n-2, broadcast) and
    any path that needs a full-width exponent. ebits = static exponent
    bit-width. Branchless left-to-right ladder over all ebits bits.
    exit_domain=False returns the result in Montgomery form (the EC
    scalar path multiplies it straight into other Montgomery values);
    s_in_mont=True skips the domain entry for an already-Montgomery s.
    """
    s_m = s if s_in_mont else mont_mul(s, r2, n, nprime)

    def body(i, x):
        bit_idx = ebits - 1 - i
        limb = bit_idx // LIMB_BITS
        shift = bit_idx % LIMB_BITS
        bit = (e_limbs[limb] >> shift) & 1
        x = mont_sqr(x, n, nprime)
        mult = mont_mul(x, s_m, n, nprime)
        return jnp.where(bit[None, :].astype(bool), mult, x)

    x = lax.fori_loop(0, ebits, body, one_mont)
    if not exit_domain:
        return x
    one = jnp.zeros_like(s).at[0].set(1)
    return mont_mul(x, one, n, nprime)


# ---------------------------------------------------------------------------
# Modular add/sub (used by the EC layer; operands already reduced < m)
# ---------------------------------------------------------------------------

def add_mod(a: jnp.ndarray, b: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """(a + b) mod m over [K, N] normalized limb arrays, a, b < m."""
    k = a.shape[0]
    zero_row = jnp.zeros_like(a[:1])
    t = carry_normalize(jnp.concatenate([a + b, zero_row], axis=0))
    m_pad = jnp.concatenate([m, zero_row], axis=0)
    ge = compare_ge(t, m_pad)
    return sub_where(t, m_pad, ge)[:k]


def sub_mod(a: jnp.ndarray, b: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """(a - b) mod m over [K, N] normalized limb arrays, a, b < m."""
    k = a.shape[0]
    zero_row = jnp.zeros_like(a[:1])
    # a + m - b: always non-negative, < 2m.
    t = carry_normalize(jnp.concatenate([a + m, zero_row], axis=0))
    b_pad = jnp.concatenate([b, zero_row], axis=0)
    t = sub_where(t, b_pad, jnp.ones(a.shape[1], dtype=bool))
    m_pad = jnp.concatenate([m, zero_row], axis=0)
    ge = compare_ge(t, m_pad)
    return sub_where(t, m_pad, ge)[:k]


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """[K, N] normalized limbs → [N] bool: value == 0."""
    return jnp.all(a == 0, axis=0)


# ---------------------------------------------------------------------------
# Host-side Montgomery precomputation (per key; plain Python ints)
# ---------------------------------------------------------------------------

def mont_params(n_int: int, k: int):
    """Montgomery constants for modulus n with R = 2^(16k).

    Returns (nprime_int, r2_int, one_mont_int):
    N' = -n⁻¹ mod R;  R² mod n;  R mod n.
    """
    if n_int % 2 == 0:
        raise ValueError("modulus must be odd")
    r = 1 << (LIMB_BITS * k)
    if n_int >= r:
        raise ValueError("modulus does not fit in k limbs")
    n_inv = pow(n_int, -1, r)
    nprime = (-n_inv) % r
    return nprime, (r * r) % n_int, r % n_int
