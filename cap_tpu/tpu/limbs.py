"""Host-side limb packing: Python ints / big-endian bytes ↔ limb arrays.

Numbers are little-endian base-2^16 limb vectors. Device arrays are
limb-first ([K, N]); host packing produces numpy arrays in that layout.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence

import numpy as np

LIMB_BITS = 16
LIMB_BASE = 1 << LIMB_BITS
LIMB_MASK = LIMB_BASE - 1


def nlimbs_for_bits(bits: int) -> int:
    return (bits + LIMB_BITS - 1) // LIMB_BITS


def int_to_limbs(value: int, k: int) -> np.ndarray:
    """One int → [k] uint32 little-endian limb vector."""
    if value < 0:
        raise ValueError("negative values are not representable")
    if value >> (k * LIMB_BITS):
        raise ValueError(f"value does not fit in {k} limbs")
    out = np.empty(k, dtype=np.uint32)
    for i in range(k):
        out[i] = value & LIMB_MASK
        value >>= LIMB_BITS
    return out


def ints_to_limbs(values: Sequence[int], k: int) -> np.ndarray:
    """N ints → [k, N] uint32 limb-first array."""
    n = len(values)
    out = np.empty((k, n), dtype=np.uint32)
    for j, v in enumerate(values):
        out[:, j] = int_to_limbs(v, k)
    return out


def limbs_to_int(limbs: np.ndarray) -> int:
    """[k] limb vector → int (limbs need not be normalized)."""
    value = 0
    for i in range(limbs.shape[0] - 1, -1, -1):
        value = (value << LIMB_BITS) + int(limbs[i])
    return value


def limbs_to_ints(limbs: np.ndarray) -> List[int]:
    """[k, N] limb-first array → list of N ints."""
    return [limbs_to_int(limbs[:, j]) for j in range(limbs.shape[1])]


def bytes_be_to_limbs(chunks: Iterable[bytes], k: int) -> np.ndarray:
    """N big-endian byte strings → [k, N] limb array (vectorized).

    All chunks must have length ≤ 2*k bytes. This is the hot host-side
    conversion (signatures and hashes into device layout), so it works
    on a padded [N, 2k] byte matrix instead of per-item Python ints.
    """
    chunk_list = list(chunks)
    n = len(chunk_list)
    width = 2 * k
    buf = np.zeros((n, width), dtype=np.uint8)
    for j, c in enumerate(chunk_list):
        if len(c) > width:
            raise ValueError(f"chunk of {len(c)} bytes exceeds {k} limbs")
        if c:
            buf[j, width - len(c):] = np.frombuffer(c, dtype=np.uint8)
    # big-endian bytes → little-endian 16-bit limbs
    hi = buf[:, 0::2].astype(np.uint32)
    lo = buf[:, 1::2].astype(np.uint32)
    limbs_be = (hi << 8) | lo          # [N, k] most-significant limb first
    return limbs_be[:, ::-1].T.copy()  # → [k, N] little-endian, limb-first


def right_align_bytes(mat: np.ndarray, lens: np.ndarray,
                      width: int) -> np.ndarray:
    """Vectorized: left-aligned [N, W] byte rows → right-aligned [N, width].

    Row i's value occupies its first lens[i] bytes; output rows are
    zero-padded on the left (big-endian integer layout).
    """
    n, w = mat.shape
    if int(lens.max(initial=0)) > width:
        raise ValueError("value exceeds capacity")
    cols = np.arange(width)[None, :]
    src = cols - (width - lens[:, None])
    valid = src >= 0
    return np.where(valid, mat[np.arange(n)[:, None],
                               np.clip(src, 0, w - 1)], 0).astype(np.uint8)


def bytes_to_limbs_device(mat):
    """Device: [N, 2K] u8 right-aligned big-endian → [K, N] u32 limbs.

    The host ships raw bytes (half the wire size of u32 limb arrays —
    host↔device bandwidth is the scarce resource on tunneled setups);
    the big-endian-bytes → little-endian-limbs transform runs on
    device.
    """
    import jax.numpy as jnp

    m = mat.astype(jnp.uint32)
    hi = m[:, 0::2]
    lo = m[:, 1::2]
    return ((hi << 8) | lo)[:, ::-1].T


def bytes_matrix_to_limbs(mat: np.ndarray, lens: np.ndarray,
                          k: int) -> np.ndarray:
    """Vectorized: left-aligned big-endian byte rows → [k, N] limb array.

    mat: [N, W] uint8 with each row's value occupying its first lens[i]
    bytes (tail is padding). Values longer than 2*k bytes raise.
    """
    buf = right_align_bytes(mat, lens, 2 * k)
    hi = buf[:, 0::2].astype(np.uint32)
    lo = buf[:, 1::2].astype(np.uint32)
    limbs_be = (hi << 8) | lo
    return limbs_be[:, ::-1].T.copy()


def limbs_to_bytes_be(limbs: np.ndarray, nbytes: int) -> List[bytes]:
    """[k, N] limb array → N big-endian byte strings of length nbytes."""
    k, n = limbs.shape
    if nbytes > 2 * k:
        raise ValueError("nbytes exceeds limb capacity")
    le = limbs.T.astype(np.uint32)                   # [N, k] little-endian
    be = le[:, ::-1]                                 # most-significant first
    out = np.empty((n, 2 * k), dtype=np.uint8)
    out[:, 0::2] = (be >> 8).astype(np.uint8)
    out[:, 1::2] = (be & 0xFF).astype(np.uint8)
    return [out[j, 2 * k - nbytes:].tobytes() for j in range(n)]
