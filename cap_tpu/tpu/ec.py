"""Batched ECDSA verification (P-256/P-384/P-521) as JAX/XLA programs.

Replaces crypto/ecdsa.Verify — the reference's ES* hot loop
(jwt/keyset.go:126-139 → go-jose → Go stdlib) — with TPU-shaped batch
arithmetic over the limb machinery in ``bignum``:

- per-curve Montgomery constants for BOTH the field (mod p) and the
  scalar group (mod n), broadcast across the batch;
- w = s⁻¹ mod n via Montgomery's simultaneous-inversion product tree
  (``bignum.batch_mont_inverse``): ~3 multiplies per token instead of
  a 2·nbits-multiply Fermat ladder per token;
- u1·G + u2·Q by interleaved fixed-window recoding (w = 4): scalars
  split into 4-bit digits d_i, and the sum becomes
  Σ d1_i·(2^{4i}G) + Σ d2_i·(2^{4i}Q) — every 2^{4i}-multiple is
  PRECOMPUTED host-side (G per curve; Q per key, into the
  device-resident key table — the key-gather axis, SURVEY.md §2.6),
  so the device ladder is just 2·⌈nbits/4⌉ mixed additions with
  per-token table gathers and ZERO doublings;
- mixed Jacobian/affine addition — complete for the inputs the ladder
  produces, EXCEPT the same-x exceptional cases (addend ==
  ±accumulator), which are flagged per token and re-verified on the
  CPU oracle (unreachable for honest signatures, adversarially
  constructible — parity must hold there too);
- the final check is projective: accept iff X ≡ r·Z² or, when
  r + n < p, X ≡ (r+n)·Z² (mod p) — no field inversion anywhere.

Everything is shape-static; one compilation per (curve, batch-size)
bucket.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import limbs as L


def ladder_mode() -> str:
    """Window-add law for the ES* ladders: ``jacobian`` (default) or
    ``affine``.

    ``affine`` replaces the 11-mul mixed Jacobian/affine window madd
    with a 2M+1S affine add whose per-lane division is amortized by ONE
    batched product-tree inversion mod p across all lanes per window
    step (the round-5 verdict's A/B ask). Selectable per keyset
    (``TPUBatchKeySet(ec_ladder=...)``) or globally via
    ``CAP_TPU_EC_LADDER=affine``; docs/PERF.md records the measured
    A/B and why the default stays Jacobian.
    """
    v = os.environ.get("CAP_TPU_EC_LADDER", "").strip().lower()
    return "affine" if v == "affine" else "jacobian"


def resolve_ladder(ladder: Optional[str]) -> str:
    if ladder is None:
        return ladder_mode()
    if ladder not in ("jacobian", "affine"):
        raise ValueError(f"unknown EC ladder mode {ladder!r}")
    return ladder

# NIST curve domain parameters (FIPS 186-4 / SEC 2).
_CURVE_INTS = {
    "P-256": dict(
        p=0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF,
        n=0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551,
        gx=0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296,
        gy=0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5,
        coord_bytes=32,
    ),
    "P-384": dict(
        p=(1 << 384) - (1 << 128) - (1 << 96) + (1 << 32) - 1,
        n=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFC7634D81F4372DDF581A0DB248B0A77AECEC196ACCC52973,  # noqa: E501
        gx=0xAA87CA22BE8B05378EB1C71EF320AD746E1D3B628BA79B9859F741E082542A385502F25DBF55296C3A545E3872760AB7,  # noqa: E501
        gy=0x3617DE4A96262C6F5D9E98BF9292DC29F8F41DBD289A147CE9DA3113B5F0B8C00A60B1CE1D7E819D7A431D7C90EA0E5F,  # noqa: E501
        coord_bytes=48,
    ),
    "P-521": dict(
        p=(1 << 521) - 1,
        n=int("01fffffffffffffffffffffffffffffffffffffffffffffffffffffff"
              "ffffffffffa51868783bf2f966b7fcc0148f709a5d03bb5c9b8899c47"
              "aebb6fb71e91386409", 16),
        gx=0x00C6858E06B70404E9CD9E3ECB662395B4429C648139053FB521F828AF606B4D3DBAA14B5E77EFE75928FE1DC127A2FFA8DE3348B3C1856A429BF97E7E31C2E5BD66,  # noqa: E501
        gy=0x011839296A789A3BC0045C8A5FB42C7D1BD998F54449579B446817AFBD17273E662C97EE72995EF42640C550B9013FAD0761353C7086A272C24088BE94769FD16650,  # noqa: E501
        coord_bytes=66,
    ),
}


class CurveParams:
    """Host-side per-curve constants (ints + packed limb arrays)."""

    def __init__(self, name: str):
        from .bignum import mont_params

        c = _CURVE_INTS[name]
        self.name = name
        self.p: int = c["p"]
        self.n: int = c["n"]
        self.gx: int = c["gx"]
        self.gy: int = c["gy"]
        self.coord_bytes: int = c["coord_bytes"]
        self.nbits: int = self.n.bit_length()
        self.k: int = L.nlimbs_for_bits(self.p.bit_length())

        k = self.k
        self.p_limbs = L.int_to_limbs(self.p, k)
        self.n_limbs = L.int_to_limbs(self.n, k)
        pprime, pr2, pone = mont_params(self.p, k)
        nprime, nr2, none_ = mont_params(self.n, k)
        self.pprime_limbs = L.int_to_limbs(pprime, k)
        self.pr2_limbs = L.int_to_limbs(pr2, k)
        self.pone_limbs = L.int_to_limbs(pone, k)
        self.nprime_limbs = L.int_to_limbs(nprime, k)
        self.nr2_limbs = L.int_to_limbs(nr2, k)
        self.none_limbs = L.int_to_limbs(none_, k)
        self.nm2_limbs = L.int_to_limbs(self.n - 2, k)   # Fermat exponent
        # Field-side Fermat exponent p−2: the affine ladder's batched
        # inversion tree inverts its root mod p (the Jacobian ladder
        # never inverts in the field).
        self.pbits: int = self.p.bit_length()
        self.pm2_limbs = L.int_to_limbs(self.p - 2, k)
        # G in field-Montgomery form.
        r_mod_p = pone
        self.gx_m = L.int_to_limbs(self.gx * r_mod_p % self.p, k)
        self.gy_m = L.int_to_limbs(self.gy * r_mod_p % self.p, k)
        # 4-bit interleaved-window recoding: ⌈nbits/4⌉ digit positions.
        self.n_windows = (self.nbits + 3) // 4
        self._dev_consts = None
        self._g_tables = None

    def device_consts(self):
        """Cached [K, 1] device arrays of every broadcast curve constant
        (transferred once per curve, broadcast on-device in the core)."""
        if self._dev_consts is None:
            self._dev_consts = tuple(
                jnp.asarray(v)[:, None] for v in (
                    self.p_limbs, self.pprime_limbs, self.pr2_limbs,
                    self.pone_limbs, self.n_limbs, self.nprime_limbs,
                    self.nr2_limbs, self.none_limbs, self.nm2_limbs,
                    self.gx_m, self.gy_m, self.pm2_limbs))
        return self._dev_consts

    # -- host affine arithmetic (table precompute only) -------------------

    def affine_add(self, P: Optional[Tuple[int, int]],
                   Q: Optional[Tuple[int, int]]) -> Optional[Tuple[int, int]]:
        p = self.p
        if P is None:
            return Q
        if Q is None:
            return P
        x1, y1 = P
        x2, y2 = Q
        if x1 == x2:
            if (y1 + y2) % p == 0:
                return None
            lam = (3 * x1 * x1 - 3) * pow(2 * y1, -1, p) % p
        else:
            lam = (y2 - y1) * pow(x2 - x1, -1, p) % p
        x3 = (lam * lam - x1 - x2) % p
        y3 = (lam * (x1 - x3) - y1) % p
        return x3, y3

    def window_rows(self, point: Tuple[int, int]):
        """Host precompute of the 4-bit window table for one point.

        Returns (rows_x, rows_y): [n_windows·15, K] uint32 limb rows in
        field-Montgomery form; row i·15 + (d−1) holds d·2^{4i}·point.
        Never hits infinity: the point has prime order n and
        d·2^{4i} < 16·2^nbits is never ≡ 0 (mod n) for d ∈ [1, 15].
        """
        r_mod_p = L.limbs_to_int(self.pone_limbs)
        nw, k = self.n_windows, self.k
        rows_x = np.empty((nw * 15, k), np.uint32)
        rows_y = np.empty((nw * 15, k), np.uint32)
        base = point
        for i in range(nw):
            acc = None
            for d in range(1, 16):
                acc = self.affine_add(acc, base)
                x, y = acc
                rows_x[i * 15 + d - 1] = L.int_to_limbs(
                    x * r_mod_p % self.p, k)
                rows_y[i * 15 + d - 1] = L.int_to_limbs(
                    y * r_mod_p % self.p, k)
            for _ in range(4):
                base = self.affine_add(base, base)
        return rows_x, rows_y

    def g_tables(self):
        """Cached device window table for the fixed base point G."""
        if self._g_tables is None:
            gx_rows, gy_rows = self.window_rows((self.gx, self.gy))
            self._g_tables = (jnp.asarray(gx_rows), jnp.asarray(gy_rows))
        return self._g_tables

    # -- fast window-table precompute (Jacobian + one batched inverse) ----

    def window_multiples(self, point: Tuple[int, int], w_bits: int,
                         n_windows: int) -> Tuple[list, list]:
        """All d·2^{w·i}·point (d ∈ [1, 2^w−1], i ∈ [0, n_windows)) as
        affine int lists, row order i·(2^w−1) + (d−1).

        The naive per-row affine chain costs one modular inversion per
        point; here the chain runs in Jacobian coordinates (no
        inversions) and ONE batched Montgomery-trick inversion converts
        every row to affine — the difference between seconds and
        minutes for the 12-bit tables (2^12−1 rows × 22 windows/key).
        Never hits infinity: d·2^{w·i} < 2^{w·n_windows + w} is never
        ≡ 0 mod n for the prime-order base points used here.
        """
        p = self.p
        per = (1 << w_bits) - 1
        rows = n_windows * per
        JX = [0] * rows
        JY = [0] * rows
        JZ = [0] * rows
        bx, by = point

        def jdouble(X1, Y1, Z1):
            # dbl-2001-b (a = -3)
            delta = Z1 * Z1 % p
            gamma = Y1 * Y1 % p
            beta = X1 * gamma % p
            alpha = 3 * (X1 - delta) * (X1 + delta) % p
            X3 = (alpha * alpha - 8 * beta) % p
            Z3 = ((Y1 + Z1) ** 2 - gamma - delta) % p
            Y3 = (alpha * (4 * beta - X3) - 8 * gamma * gamma) % p
            return X3, Y3, Z3

        def jmadd(X1, Y1, Z1, x2, y2):
            # madd-2004-hmv (Z2 = 1); caller guarantees the points are
            # distinct and nonzero, so h ≠ 0.
            z1z1 = Z1 * Z1 % p
            u2 = x2 * z1z1 % p
            s2 = y2 * Z1 % p * z1z1 % p
            h = (u2 - X1) % p
            hh = h * h % p
            i4 = 4 * hh % p
            j = h * i4 % p
            r = 2 * (s2 - Y1) % p
            v = X1 * i4 % p
            X3 = (r * r - j - 2 * v) % p
            Y3 = (r * (v - X3) - 2 * Y1 * j) % p
            Z3 = ((Z1 + h) ** 2 - z1z1 - hh) % p
            return X3, Y3, Z3

        for i in range(n_windows):
            base_row = i * per
            # d = 1: the (affine) base itself
            JX[base_row], JY[base_row], JZ[base_row] = bx, by, 1
            if per > 1:
                X, Y, Z = jdouble(bx, by, 1)         # d = 2
                JX[base_row + 1], JY[base_row + 1], JZ[base_row + 1] = \
                    X, Y, Z
                for d in range(3, per + 1):
                    X, Y, Z = jmadd(X, Y, Z, bx, by)
                    r = base_row + d - 1
                    JX[r], JY[r], JZ[r] = X, Y, Z
            # advance base by 2^w for the next window
            BX, BY, BZ = bx, by, 1
            for _ in range(w_bits):
                BX, BY, BZ = jdouble(BX, BY, BZ)
            zi = pow(BZ, -1, p)
            zi2 = zi * zi % p
            bx, by = BX * zi2 % p, BY * zi2 % p * zi % p

        # One batched inversion of all Z (Montgomery's trick).
        pref = [1] * (rows + 1)
        for r in range(rows):
            pref[r + 1] = pref[r] * JZ[r] % p
        inv = pow(pref[rows], -1, p)
        X_out = [0] * rows
        Y_out = [0] * rows
        for r in range(rows - 1, -1, -1):
            zi = pref[r] * inv % p       # = JZ[r]^-1
            inv = inv * JZ[r] % p
            zi2 = zi * zi % p
            X_out[r] = JX[r] * zi2 % p
            Y_out[r] = JY[r] * zi2 % p * zi % p
        return X_out, Y_out


_CURVES_CACHE: Dict[str, CurveParams] = {}


def curve(name: str) -> CurveParams:
    if name not in _CURVES_CACHE:
        _CURVES_CACHE[name] = CurveParams(name)
    return _CURVES_CACHE[name]


class ECKeyTable:
    """Device-resident table of EC public keys for one curve.

    Per key, the full 4-bit interleaved-window table (d·2^{4i}·Q for
    d ∈ [1,15], i ∈ [0, n_windows)) in affine field-Montgomery form —
    the scalar-mult ladder then needs no doublings at all, only gathers
    + mixed adds (the key-gather axis, SURVEY.md §2.6).
    """

    def __init__(self, crv: str, keys: Sequence):
        self.curve = curve(crv)
        self.keys = list(keys)  # cryptography EllipticCurvePublicKey
        self.coord_bytes = self.curve.coord_bytes
        cp = self.curve
        k = cp.k
        nk = len(self.keys)
        rows = cp.n_windows * 15
        qx_rows = np.empty((nk * rows, k), np.uint32)
        qy_rows = np.empty((nk * rows, k), np.uint32)
        for i, key in enumerate(self.keys):
            nums = key.public_numbers()
            rx, ry = cp.window_rows((nums.x, nums.y))
            qx_rows[i * rows:(i + 1) * rows] = rx
            qy_rows[i * rows:(i + 1) * rows] = ry
        self.tqx = jnp.asarray(qx_rows)
        self.tqy = jnp.asarray(qy_rows)
        self._rns = None

    def rns(self):
        """Lazily-built RNS-form window tables (accelerator path)."""
        if self._rns is None:
            from . import ec_rns

            self._rns = ec_rns.ECRNSKeyTable(self.curve.name, self.keys)
        return self._rns


# ---------------------------------------------------------------------------
# Device kernels (all values in field-Montgomery form unless noted)
# ---------------------------------------------------------------------------

def _jac_double(X, Y, Z, p, pp):
    """Jacobian doubling, a = -3 (all NIST curves). 8 field muls.

    Safe at infinity (Z=0 → Z3=0) and for Y=0 (absent on prime-order
    curves).
    """
    from . import bignum as B

    delta = B.mont_mul(Z, Z, p, pp)
    gamma = B.mont_mul(Y, Y, p, pp)
    beta = B.mont_mul(X, gamma, p, pp)
    t1 = B.sub_mod(X, delta, p)
    t2 = B.add_mod(X, delta, p)
    t3 = B.mont_mul(t1, t2, p, pp)
    alpha = B.add_mod(B.add_mod(t3, t3, p), t3, p)
    beta4 = B.add_mod(B.add_mod(beta, beta, p), B.add_mod(beta, beta, p), p)
    beta8 = B.add_mod(beta4, beta4, p)
    X3 = B.sub_mod(B.mont_mul(alpha, alpha, p, pp), beta8, p)
    yz = B.add_mod(Y, Z, p)
    Z3 = B.sub_mod(B.sub_mod(B.mont_mul(yz, yz, p, pp), gamma, p), delta, p)
    g2 = B.mont_mul(gamma, gamma, p, pp)
    g8 = B.add_mod(B.add_mod(g2, g2, p), B.add_mod(g2, g2, p), p)
    g8 = B.add_mod(g8, g8, p)
    Y3 = B.sub_mod(
        B.mont_mul(alpha, B.sub_mod(beta4, X3, p), p, pp), g8, p)
    return X3, Y3, Z3


def _jac_madd(X1, Y1, Z1, x2, y2, p, pp, one_m):
    """Mixed Jacobian + affine addition. 11 field muls.

    Returns (X3, Y3, Z3, degenerate): the exceptional same-x cases
    (P == ±(x2, y2)) are NOT computed — they set ``degenerate`` so the
    caller can re-verify those tokens on the CPU oracle. P at infinity
    is handled (returns the affine addend).
    """
    from . import bignum as B

    z1z1 = B.mont_mul(Z1, Z1, p, pp)
    u2 = B.mont_mul(x2, z1z1, p, pp)
    z1_3 = B.mont_mul(Z1, z1z1, p, pp)
    s2 = B.mont_mul(y2, z1_3, p, pp)
    h = B.sub_mod(u2, X1, p)
    hh = B.mont_mul(h, h, p, pp)
    i4 = B.add_mod(B.add_mod(hh, hh, p), B.add_mod(hh, hh, p), p)
    j = B.mont_mul(h, i4, p, pp)
    s2y1 = B.sub_mod(s2, Y1, p)
    rr = B.add_mod(s2y1, s2y1, p)
    v = B.mont_mul(X1, i4, p, pp)
    r2_ = B.mont_mul(rr, rr, p, pp)
    X3 = B.sub_mod(B.sub_mod(r2_, j, p), B.add_mod(v, v, p), p)
    y1j = B.mont_mul(Y1, j, p, pp)
    Y3 = B.sub_mod(
        B.mont_mul(rr, B.sub_mod(v, X3, p), p, pp),
        B.add_mod(y1j, y1j, p),
        p,
    )
    zh = B.add_mod(Z1, h, p)
    Z3 = B.sub_mod(B.sub_mod(B.mont_mul(zh, zh, p, pp), z1z1, p), hh, p)

    p_inf = B.is_zero(Z1)
    eq_x = B.is_zero(h)
    degenerate = ~p_inf & eq_x  # both the double case and the ±inverse case

    sel = p_inf[None, :]
    X3 = jnp.where(sel, x2, X3)
    Y3 = jnp.where(sel, y2, Y3)
    Z3 = jnp.where(sel, one_m, Z3)
    return X3, Y3, Z3, degenerate


def _affine_madd(x, y, inf, ax, ay, has, p, pp, one_m,
                 p1, pp1, pr2_1, pone1, pm2_1, pbits: int):
    """Batched affine + affine addition, 2M + 1S + one batched inverse.

    x, y: [K, M] affine accumulator (field-Montgomery form, canonical);
    inf: [M] explicit infinity lane; ax, ay: gathered table points
    (never at infinity); has: [M] lanes that add this step (digit > 0).
    The per-lane division λ = (ay−y)/(ax−x) is ONE product-tree
    inversion mod p across all M lanes (``bignum.batch_mont_inverse``
    with the field constants p1..pm2_1 [K, 1]), so the per-lane
    multiply count is 3 + ~3 tree multiplies instead of the Jacobian
    madd's 11. The exceptional cases the complete Jacobian law absorbs
    are explicit here:

    - infinity accumulator → masked select of the addend (lift);
    - doubling (P == Q) and inverse (P == −Q), both x(P) == x(ax) →
      flagged ``degenerate`` (the caller re-verifies on the CPU
      oracle, the same contract as ``_jac_madd``), with the zero
      denominator replaced by 1 so the inversion tree stays
      invertible.

    Returns (x3, y3, inf3, degenerate).
    """
    from . import bignum as B

    dx = B.sub_mod(ax, x, p)
    eqx = B.is_zero(dx)
    live = has & ~inf
    degenerate = live & eqx
    den = jnp.where((live & ~eqx)[None, :], dx, one_m)
    inv = B.batch_mont_inverse(den, p1, pp1, pr2_1, pone1, pm2_1,
                               nbits=pbits)
    dy = B.sub_mod(ay, y, p)
    lam = B.mont_mul(dy, inv, p, pp)
    sq = B.mont_mul(lam, lam, p, pp)
    x3 = B.sub_mod(B.sub_mod(sq, x, p), ax, p)
    y3 = B.sub_mod(B.mont_mul(lam, B.sub_mod(x, x3, p), p, pp), y, p)

    lift = (inf & has)[None, :]
    x3 = jnp.where(lift, ax, x3)
    y3 = jnp.where(lift, ay, y3)
    sel = has[None, :]
    return (jnp.where(sel, x3, x), jnp.where(sel, y3, y),
            inf & ~has, degenerate)


@partial(jax.jit, static_argnames=("nbits", "n_windows", "pbits",
                                   "ladder"))
def _ecdsa_core(r, s, e, key_idx, tqx, tqy, tgx, tgy,
                p, pp, pr2, pone, n, npp, nr2, none_, nm2, gx, gy, pm2,
                nbits: int, n_windows: int, pbits: int = 0,
                ladder: str = "jacobian"):
    """Batched ECDSA verify core.

    r, s, e: [K, N] plain limb values (signature halves, hash int);
    N must be a power of two (the batch-inverse tree pairs it down).
    key_idx: [N] int32 rows into the per-key window tables
    tqx/tqy: [nk·n_windows·15, K]; tgx/tgy: [n_windows·15, K] for G.
    Remaining args: [K, 1] curve constants (broadcast on-device here —
    transferred once per curve, not per batch).

    ``ladder`` selects the window-add law: ``jacobian`` (the complete
    mixed madd, interleaved G/Q chains in one accumulator) or
    ``affine`` (two lane-concatenated affine chains, one batched
    product-tree inversion mod p per window step — see
    :func:`ladder_mode`). Verdicts are bit-exact across both (the
    affine parity suite pins it).

    Returns (ok [N], degenerate [N]).
    """
    from . import bignum as B

    k = r.shape[0]
    shape = r.shape
    n1, npp1, nr21, none1, nm21 = n, npp, nr2, none_, nm2
    p1, pp1, pr2_1, pone1, pm2_1 = p, pp, pr2, pone, pm2
    (p, pp, pr2, pone, n, npp, nr2) = (
        jnp.broadcast_to(a, shape)
        for a in (p, pp, pr2, pone, n, npp, nr2))

    # 1. Range checks: 1 <= r, s < n.
    r_ok = ~B.is_zero(r) & ~B.compare_ge(r, n)
    s_ok = ~B.is_zero(s) & ~B.compare_ge(s, n)

    # 2. w = s⁻¹ mod n via the batch product-tree inverse (Montgomery
    #    domain). Invalid s (0 or ≥ n) is replaced by 1 so the tree
    #    stays invertible; those tokens are rejected by s_ok anyway.
    one_plain = jnp.zeros_like(r).at[0].set(1)
    s_safe = jnp.where(s_ok[None, :], s, one_plain)
    s_m = B.mont_mul(s_safe, nr2, n, npp)
    w_m = B.batch_mont_inverse(s_m, n1, npp1, nr21, none1, nm21,
                               nbits=nbits)

    # 3. u1 = e·w mod n, u2 = r·w mod n (plain limb values: montmul of a
    #    plain operand with a Montgomery operand cancels the R factor).
    u1 = B.mont_mul(e, w_m, n, npp)
    u2 = B.mont_mul(r, w_m, n, npp)

    # 4. Interleaved-window ladder: R = Σ d1_i·(2^{4i}G) + d2_i·(2^{4i}Q).
    #    4-bit digits, little-endian across limbs (LIMB_BITS = 16 → 4
    #    nibbles per limb); no doublings — all multiples precomputed.
    def nibbles(u):
        return jnp.stack(
            [(u >> (4 * j)) & 15 for j in range(4)], axis=1
        ).reshape(4 * k, shape[1]).astype(jnp.int32)

    dig1 = nibbles(u1)
    dig2 = nibbles(u2)
    key_base = key_idx.astype(jnp.int32) * (n_windows * 15)

    if ladder == "affine":
        return _ecdsa_affine_tail(
            r, r_ok, s_ok, dig1, dig2, key_base, tqx, tqy, tgx, tgy,
            p, pp, pr2, pone, n,
            p1, pp1, pr2_1, pone1, pm2_1,
            k=k, n_windows=n_windows, pbits=pbits)

    zeros = jnp.zeros_like(r)
    X0, Y0, Z0 = pone, pone, zeros          # point at infinity (Z = 0)
    deg0 = jnp.zeros(r.shape[1], dtype=bool)

    def add_from_table(carry, tab_x, tab_y, d, row0):
        X, Y, Z, deg = carry
        has = d > 0
        idx = row0 + jnp.where(has, d - 1, 0)
        ax = jnp.take(tab_x, idx, axis=0).T      # [K, N]
        ay = jnp.take(tab_y, idx, axis=0).T
        Xa, Ya, Za, dd = _jac_madd(X, Y, Z, ax, ay, p, pp, pone)
        sel = has[None, :]
        return (jnp.where(sel, Xa, X), jnp.where(sel, Ya, Y),
                jnp.where(sel, Za, Z), deg | (dd & has))

    def ladder_body(i, carry):
        d1 = lax.dynamic_slice_in_dim(dig1, i, 1, axis=0)[0]
        d2 = lax.dynamic_slice_in_dim(dig2, i, 1, axis=0)[0]
        carry = add_from_table(carry, tgx, tgy, d1, i * 15)
        carry = add_from_table(carry, tqx, tqy, d2, key_base + i * 15)
        return carry

    X, Y, Z, deg = lax.fori_loop(0, n_windows, ladder_body,
                                 (X0, Y0, Z0, deg0))

    not_inf = ~B.is_zero(Z)

    # 5. Projective check: X == r·Z² or X == (r+n)·Z² (mod p).
    z2 = B.mont_mul(Z, Z, p, pp)
    r_pm = B.mont_mul(r, pr2, p, pp)        # r < n < p → valid lift
    rhs1 = B.mont_mul(r_pm, z2, p, pp)
    ok1 = jnp.all(X == rhs1, axis=0)

    zero_row = jnp.zeros_like(r[:1])
    rpn = B.carry_normalize(jnp.concatenate([r + n, zero_row], axis=0))
    p_pad = jnp.concatenate([p, zero_row], axis=0)
    rpn_lt_p = ~B.compare_ge(rpn, p_pad)
    rpn_k = rpn[:k]                         # < p when rpn_lt_p
    rpn_pm = B.mont_mul(rpn_k, pr2, p, pp)
    rhs2 = B.mont_mul(rpn_pm, z2, p, pp)
    ok2 = jnp.all(X == rhs2, axis=0) & rpn_lt_p

    ok = r_ok & s_ok & not_inf & (ok1 | ok2)
    return ok, deg & r_ok & s_ok


def _ecdsa_affine_tail(r, r_ok, s_ok, dig1, dig2, key_base,
                       tqx, tqy, tgx, tgy,
                       p, pp, pr2, pone, n,
                       p1, pp1, pr2_1, pone1, pm2_1,
                       k: int, n_windows: int, pbits: int):
    """Affine-ladder tail of the limb-engine verify core.

    The G-digit and Q-digit chains run as TWO lane-concatenated affine
    accumulators ([K, 2N] state), so each window step is ONE affine add
    whose divisions amortize into a single batched product-tree
    inversion over all 2N lanes; the chains merge with one more affine
    add (one inversion over N lanes) and the final check is a direct
    field compare x == r·R mod p — no Z coordinate anywhere.

    Separate chains also shrink the degenerate surface: a single
    prefix-sum chain of one scalar u < n can never hit its own window
    multiple (every partial sum and addend are distinct multiples
    d·P with 0 < d < n of a prime-order point), so in-ladder ``deg``
    flags are adversarially unreachable and only the MERGE can
    degenerate (u1·G == ±u2·Q) — still flagged and CPU-re-verified,
    same contract as the Jacobian path.
    """
    from . import bignum as B

    n_tok = r.shape[1]
    shape2 = (k, 2 * n_tok)
    p2, pp2, pone2 = (jnp.broadcast_to(a, shape2)
                      for a in (p1, pp1, pone1))

    tab_x = jnp.concatenate([tgx, tqx], axis=0)
    tab_y = jnp.concatenate([tgy, tqy], axis=0)
    g_rows = tgx.shape[0]

    x0 = jnp.broadcast_to(pone1, shape2)
    inf0 = jnp.ones(2 * n_tok, dtype=bool)
    deg0 = jnp.zeros(2 * n_tok, dtype=bool)

    def ladder_body(i, carry):
        x, y, inf, deg = carry
        d1 = lax.dynamic_slice_in_dim(dig1, i, 1, axis=0)[0]
        d2 = lax.dynamic_slice_in_dim(dig2, i, 1, axis=0)[0]
        d = jnp.concatenate([d1, d2])
        row0 = jnp.concatenate(
            [jnp.zeros((n_tok,), jnp.int32) + i * 15,
             g_rows + key_base + i * 15])
        has = d > 0
        idx = row0 + jnp.where(has, d - 1, 0)
        ax = jnp.take(tab_x, idx, axis=0).T
        ay = jnp.take(tab_y, idx, axis=0).T
        x, y, inf, dd = _affine_madd(
            x, y, inf, ax, ay, has, p2, pp2, pone2,
            p1, pp1, pr2_1, pone1, pm2_1, pbits)
        return x, y, inf, deg | dd

    x, y, inf, deg2 = lax.fori_loop(0, n_windows, ladder_body,
                                    (x0, x0, inf0, deg0))

    xg, yg = x[:, :n_tok], y[:, :n_tok]
    xq, yq = x[:, n_tok:], y[:, n_tok:]
    inf_g, inf_q = inf[:n_tok], inf[n_tok:]
    deg = deg2[:n_tok] | deg2[n_tok:]

    # Merge: one more affine add with (xq, yq) as the addend; lanes
    # whose addend is at infinity pass the G accumulator through.
    xm, ym, inf_m, ddm = _affine_madd(
        xg, yg, inf_g, xq, yq, ~inf_q, p, pp, pone,
        p1, pp1, pr2_1, pone1, pm2_1, pbits)
    deg = deg | ddm
    not_inf = ~inf_m

    # Affine final check: x == r·R or (r+n)·R (mod p), both canonical.
    r_pm = B.mont_mul(r, pr2, p, pp)
    ok1 = jnp.all(xm == r_pm, axis=0)

    zero_row = jnp.zeros_like(r[:1])
    rpn = B.carry_normalize(jnp.concatenate([r + n, zero_row], axis=0))
    p_pad = jnp.concatenate([p, zero_row], axis=0)
    rpn_lt_p = ~B.compare_ge(rpn, p_pad)
    rpn_pm = B.mont_mul(rpn[:k], pr2, p, pp)
    ok2 = jnp.all(xm == rpn_pm, axis=0) & rpn_lt_p

    ok = r_ok & s_ok & not_inf & (ok1 | ok2)
    return ok, deg & r_ok & s_ok


@partial(jax.jit, static_argnames=("k",))
def _ec_prep(sig_bytes, dig, k: int):
    """Device: raw signature/digest bytes → (r, s, e) limb arrays.

    sig_bytes: [N, 2·cb] u8 (r ‖ s halves, each cb = 2k bytes wide);
    dig: [N, hlen] u8. e is the hash as an integer, left-zero-padded
    (hlen ≤ 2k for every supported alg/curve pairing).
    """
    cb = sig_bytes.shape[1] // 2
    r = L.bytes_to_limbs_device(sig_bytes[:, :cb])
    s = L.bytes_to_limbs_device(sig_bytes[:, cb:])
    hlen = dig.shape[1]
    e_mat = jnp.zeros((dig.shape[0], 2 * k), jnp.uint8)
    e_mat = e_mat.at[:, 2 * k - hlen:].set(dig)
    e = L.bytes_to_limbs_device(e_mat)
    return r, s, e


def verify_ecdsa_arrays_pending(table: ECKeyTable, sig_mat: np.ndarray,
                                sig_lens: np.ndarray,
                                hash_mat: np.ndarray, hash_len: int,
                                key_idx: np.ndarray,
                                ladder: Optional[str] = None):
    """Dispatch the ES* device work; return a finalize() → [N] bool.

    Asynchronous dispatch (see verify_pkcs1v15_arrays_pending);
    degenerate-flagged tokens are re-verified on the CPU oracle inside
    finalize, preserving bit-exact parity. ``ladder`` selects the
    window-add law (None → :func:`ladder_mode`).
    """
    ladder = resolve_ladder(ladder)
    cp = table.curve
    k = cp.k
    cb = cp.coord_bytes
    n_tok = sig_mat.shape[0]

    len_ok = sig_lens == 2 * cb
    safe = np.where(len_ok[:, None], sig_mat[:, : 2 * cb], 0)

    # Pad the batch to a power of two ≥ 128: the inverse tree pairs the
    # batch down, and pow-2 buckets bound XLA recompilation. Padding
    # rows have r = s = 0 → forced invalid, discarded below. Only raw
    # bytes cross the wire; limb conversion happens on device.
    n_pad = 128
    while n_pad < n_tok:
        n_pad *= 2
    dig = hash_mat[:, :hash_len]
    if n_pad != n_tok:
        fill = n_pad - n_tok
        safe = np.pad(safe, ((0, fill), (0, 0)))
        dig = np.pad(dig, ((0, fill), (0, 0)))
        key_idx = np.pad(np.asarray(key_idx, np.int32), (0, fill))

    r_limbs, s_limbs, e_limbs = _ec_prep(
        jnp.asarray(safe), jnp.asarray(np.ascontiguousarray(dig)), k=k)

    from .rns import use_rns

    if use_rns():
        # RNS/MXU point arithmetic (carry-free ladder); scalar math
        # stays in the limb engine inside the same jit.
        from . import ec_rns

        rtab = table.rns()
        consts = cp.device_consts()
        ok_dev, deg_dev = ec_rns._ecdsa_rns_core(
            r_limbs, s_limbs, e_limbs,
            jnp.asarray(key_idx, jnp.int32),
            rtab.tab,
            *consts[4:9],
            crv=cp.name, nbits=cp.nbits, wbits=rtab.ctx.w_bits,
            ladder=ladder,
        )
    else:
        ok_dev, deg_dev = _ecdsa_core(
            r_limbs, s_limbs, e_limbs,
            jnp.asarray(key_idx, jnp.int32),
            table.tqx, table.tqy, *cp.g_tables(),
            *cp.device_consts(),
            nbits=cp.nbits, n_windows=cp.n_windows,
            pbits=cp.pbits, ladder=ladder,
        )

    def finalize() -> np.ndarray:
        ok = np.asarray(ok_dev)[:n_tok] & len_ok
        deg = np.asarray(deg_dev)[:n_tok]
        for j in np.nonzero(deg & len_ok)[0]:
            ok[j] = _cpu_verify_one(table, int(key_idx[j]),
                                    sig_mat[j, : 2 * cb].tobytes(),
                                    hash_mat[j, :hash_len].tobytes())
        return ok

    return finalize


def verify_ecdsa_arrays(table: ECKeyTable, sig_mat: np.ndarray,
                        sig_lens: np.ndarray, hash_mat: np.ndarray,
                        hash_len: int,
                        key_idx: np.ndarray,
                        ladder: Optional[str] = None) -> np.ndarray:
    """Array-native ES* verify: [N] bool verdicts.

    sig_mat: [N, W] left-aligned JOSE raw signatures (r ‖ s, fixed
    width 2·coord_bytes); sig_lens: [N]; hash_mat: [N, ≥hash_len]
    digests; key_idx: [N] table rows. Degenerate-flagged tokens are
    re-verified on the CPU oracle for bit-exact parity.
    """
    return verify_ecdsa_arrays_pending(table, sig_mat, sig_lens,
                                       hash_mat, hash_len, key_idx,
                                       ladder=ladder)()


def _cpu_verify_one(table: ECKeyTable, row: int, sig_raw: bytes,
                    digest: bytes) -> bool:
    """CPU oracle for one (degenerate-flagged) token."""
    if not hasattr(table.keys[row], "verify"):
        # HostECPublicKey tables (no OpenSSL object behind the row)
        return _py_verify_one(table, int(row), sig_raw, digest)
    try:
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import ec as cec
        from cryptography.hazmat.primitives.asymmetric.utils import (
            Prehashed,
            encode_dss_signature,
        )
    except ImportError:
        # No OpenSSL stack in this environment: fall back to the exact
        # host-integer ECDSA oracle below (same verdicts — SEC1 §4.1.4
        # over the curve's own affine arithmetic).
        return _py_verify_one(table, int(row), sig_raw, digest)

    cb = table.curve.coord_bytes
    r = int.from_bytes(sig_raw[:cb], "big")
    s = int.from_bytes(sig_raw[cb:], "big")
    halg = {32: hashes.SHA256, 48: hashes.SHA384, 64: hashes.SHA512}[
        len(digest)]
    try:
        table.keys[row].verify(encode_dss_signature(r, s), digest,
                               cec.ECDSA(Prehashed(halg())))
        return True
    except (InvalidSignature, ValueError):
        return False


def scalar_mult(cp: CurveParams, k: int,
                P: Optional[Tuple[int, int]]) -> Optional[Tuple[int, int]]:
    """Host double-and-add k·P over the curve's affine arithmetic."""
    acc = None
    add = P
    while k:
        if k & 1:
            acc = cp.affine_add(acc, add)
        add = cp.affine_add(add, add)
        k >>= 1
    return acc


class HostECPublicKey:
    """Dependency-free EC public key for device-table construction.

    ``ECKeyTable`` only reads ``public_numbers().x/.y``; this provides
    exactly that surface from host integers, so tables (and the
    pure-integer oracle above) work where the ``cryptography`` package
    is unavailable. Not a drop-in for the OpenSSL-backed key anywhere
    else — the CPU trial-verify paths still require the real stack.
    """

    class _Numbers:
        def __init__(self, x: int, y: int):
            self.x, self.y = x, y

    def __init__(self, crv: str, x: int, y: int):
        self.curve_name = crv
        self._nums = self._Numbers(x, y)

    def public_numbers(self):
        return self._nums

    @classmethod
    def from_private(cls, crv: str, d: int) -> "HostECPublicKey":
        cp = curve(crv)
        qx, qy = scalar_mult(cp, d, (cp.gx, cp.gy))
        return cls(crv, qx, qy)


def host_ecdsa_sign(crv: str, d: int, e: int, k: int) -> Tuple[int, int]:
    """Textbook ECDSA signing over host ints (test/bench fixtures only
    — k must be unique per signature; nothing here is constant-time).
    Returns (r, s); raises if the chosen k yields r == 0 or s == 0.
    """
    cp = curve(crv)
    R = scalar_mult(cp, k, (cp.gx, cp.gy))
    r = R[0] % cp.n
    s = pow(k, -1, cp.n) * (e + r * d) % cp.n
    if r == 0 or s == 0:
        raise ValueError("degenerate nonce; pick another k")
    return r, s


def py_ecdsa_verify(cp: CurveParams, qx: int, qy: int, sig_raw: bytes,
                    digest: bytes) -> bool:
    """Pure-integer ECDSA verify (SEC1 §4.1.4), dependency-free.

    Same acceptance rule as Go crypto/ecdsa and OpenSSL — range checks
    1 <= r, s < n, left-bits hash truncation, accept iff
    (u1·G + u2·Q).x ≡ r (mod n). The oracle behind both the
    degenerate-lane re-verification and the crypto-less
    ``HostECPublicKey`` verify path in jwt/verify.py.
    """
    cb = cp.coord_bytes
    r = int.from_bytes(sig_raw[:cb], "big")
    s = int.from_bytes(sig_raw[cb:], "big")
    if not (1 <= r < cp.n and 1 <= s < cp.n):
        return False
    e = int.from_bytes(digest, "big")
    excess = 8 * len(digest) - cp.nbits
    if excess > 0:
        e >>= excess
    w = pow(s, -1, cp.n)
    u1 = (e * w) % cp.n
    u2 = (r * w) % cp.n
    R = cp.affine_add(scalar_mult(cp, u1, (cp.gx, cp.gy)),
                      scalar_mult(cp, u2, (qx, qy)))
    if R is None:
        return False
    return R[0] % cp.n == r


def _py_verify_one(table: ECKeyTable, row: int, sig_raw: bytes,
                   digest: bytes) -> bool:
    """Table-row wrapper over :func:`py_ecdsa_verify` (the oracle of
    last resort when the ``cryptography`` package is absent)."""
    nums = table.keys[row].public_numbers()
    return py_ecdsa_verify(table.curve, nums.x, nums.y, sig_raw, digest)


def verify_ecdsa_batch(table: ECKeyTable, sigs: Sequence[bytes],
                       msg_hashes: Sequence[bytes],
                       key_idx: np.ndarray,
                       ladder: Optional[str] = None) -> np.ndarray:
    """[N] bool verdicts for one ES* bucket (list-of-bytes interface)."""
    cb = table.curve.coord_bytes
    n_tok = len(sigs)
    w = 2 * cb
    sig_mat = np.zeros((n_tok, w), np.uint8)
    sig_lens = np.empty(n_tok, np.int64)
    for j, sg in enumerate(sigs):
        sig_lens[j] = len(sg)
        if len(sg) == w:
            sig_mat[j] = np.frombuffer(sg, np.uint8)
    hash_len = len(msg_hashes[0]) if msg_hashes else 32
    hash_mat = np.zeros((n_tok, hash_len), np.uint8)
    for j, h in enumerate(msg_hashes):
        hash_mat[j] = np.frombuffer(h[:hash_len], np.uint8)
    return verify_ecdsa_arrays(table, sig_mat, sig_lens, hash_mat,
                               hash_len, key_idx, ladder=ladder)


# ---------------------------------------------------------------------------
# Packed single-transfer dispatch (see rsa.py's packed section: one u8
# record matrix per chunk, one jitted program, sync deferred to the
# batch-wide wave)
# ---------------------------------------------------------------------------

ES_REC_EXTRA = 2          # trailing bytes per record: flags, key row


def es_packed_records(table: ECKeyTable, sig_mat: np.ndarray,
                      sig_lens: np.ndarray, hash_mat: np.ndarray,
                      hash_len: int, key_idx: np.ndarray) -> np.ndarray:
    """Host: packed [N, 2·cb + hash_len + 2] u8 records for one ES* chunk.

    Row layout: signature r‖s bytes (2·cb) ‖ digest (hash_len) ‖
    validity flag u8 ‖ key row u8.
    """
    cb = table.curve.coord_bytes
    len_ok = (sig_lens == 2 * cb).astype(np.uint8)
    safe = np.where(len_ok[:, None] != 0, sig_mat[:, :2 * cb], 0)
    rec = np.empty((sig_mat.shape[0], 2 * cb + hash_len + ES_REC_EXTRA),
                   np.uint8)
    rec[:, :2 * cb] = safe
    rec[:, 2 * cb:2 * cb + hash_len] = hash_mat[:, :hash_len]
    rec[:, 2 * cb + hash_len] = len_ok
    rec[:, 2 * cb + hash_len + 1] = key_idx.astype(np.uint8)
    return rec


def _es_packed_rns_impl(packed, tab, consts, *, crv: str,
                        nbits: int, wbits: int, k: int, cb: int,
                        hlen: int, ladder: str = "jacobian"):
    from . import ec_rns

    sig = packed[:, :2 * cb]
    dig = packed[:, 2 * cb:2 * cb + hlen]
    flags = packed[:, 2 * cb + hlen] != 0
    idx = packed[:, 2 * cb + hlen + 1].astype(jnp.int32)
    r, s, e = _ec_prep(sig, dig, k=k)
    ok, deg = ec_rns._ecdsa_rns_core(r, s, e, idx, tab,
                                     *consts, crv=crv, nbits=nbits,
                                     wbits=wbits, ladder=ladder)
    return ok & flags, deg & flags


def _es_packed_limb_impl(packed, tqx, tqy, g_tabs, consts, *, nbits: int,
                         n_windows: int, k: int, cb: int, hlen: int,
                         pbits: int = 0, ladder: str = "jacobian"):
    sig = packed[:, :2 * cb]
    dig = packed[:, 2 * cb:2 * cb + hlen]
    flags = packed[:, 2 * cb + hlen] != 0
    idx = packed[:, 2 * cb + hlen + 1].astype(jnp.int32)
    r, s, e = _ec_prep(sig, dig, k=k)
    ok, deg = _ecdsa_core(r, s, e, idx, tqx, tqy, *g_tabs, *consts,
                          nbits=nbits, n_windows=n_windows,
                          pbits=pbits, ladder=ladder)
    return ok & flags, deg & flags


_es_packed_jits: Dict[str, object] = {}


def _es_packed_jit(name: str, impl, static_names):
    fn = _es_packed_jits.get(name)
    if fn is None:
        fn = jax.jit(impl, static_argnames=static_names)
        _es_packed_jits[name] = fn
    return fn


def verify_es_packed_pending(table: ECKeyTable, rec: np.ndarray,
                             hash_len: int, mesh=None,
                             ladder: Optional[str] = None):
    """Dispatch one packed ES* chunk; returns device ([N] ok, [N] deg).

    Degenerate-flagged tokens (deg True) must be re-verified on the CPU
    oracle by the caller after the sync wave — same contract as
    verify_ecdsa_arrays_pending. With a mesh the record shards along
    the batch axis; tables replicate (SURVEY.md §2.6). ``ladder``
    selects the window-add law (None → :func:`ladder_mode`).
    """
    ladder = resolve_ladder(ladder)
    cp = table.curve
    if mesh is not None:
        from ..parallel.place import replicated, shard_batch

        dev = shard_batch(mesh, rec)
        place = lambda a: replicated(mesh, a)  # noqa: E731
    else:
        dev = jax.device_put(rec)
        place = lambda a: a  # noqa: E731

    from .rns import use_rns

    if use_rns():
        from . import ec_rns

        rtab = table.rns()
        consts = cp.device_consts()
        fn = _es_packed_jit("rns", _es_packed_rns_impl,
                            ("crv", "nbits", "wbits", "k", "cb",
                             "hlen", "ladder"))
        return fn(dev, place(rtab.tab),
                  tuple(place(a) for a in consts[4:9]),
                  crv=cp.name, nbits=cp.nbits, wbits=rtab.ctx.w_bits,
                  k=cp.k, cb=cp.coord_bytes, hlen=hash_len,
                  ladder=ladder)
    fn = _es_packed_jit("limb", _es_packed_limb_impl,
                        ("nbits", "n_windows", "k", "cb", "hlen",
                         "pbits", "ladder"))
    return fn(dev, place(table.tqx), place(table.tqy),
              tuple(place(a) for a in cp.g_tables()),
              tuple(place(a) for a in cp.device_consts()),
              nbits=cp.nbits,
              n_windows=cp.n_windows, k=cp.k, cb=cp.coord_bytes,
              hlen=hash_len, pbits=cp.pbits, ladder=ladder)
