"""Batched SHA-256 as a JAX program (FIPS 180-4, from the spec).

Device-side hashing for the PSS verify tail: EMSA-PSS-VERIFY needs
MGF1(H, dbLen) (fixed-short seeds) and H' = SHA-256(0^8 ‖ mHash ‖ salt)
(variable-length messages). Doing both ON DEVICE removes the PS* paths'
EM download entirely — only a [N] bool crosses back (the reference
computes this on the CPU per token via crypto/rsa.VerifyPSS,
/root/reference/jwt/keyset.go:126-139).

Everything is uint32 elementwise over the batch lane axis — long chains
of adds/rotates that XLA fuses into a handful of kernels; per-token
message lengths are handled by running the maximum block count and
snapshotting each token's state after ITS final block.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax.numpy as jnp

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
    0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
    0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
    0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
    0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
    0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
    0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
    0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
    0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], np.uint32)

_H0 = np.array([0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19],
               np.uint32)


def _ror(x, r: int):
    return (x >> r) | (x << (32 - r))


def _unrolled() -> bool:
    """Fully unrolled rounds: opt-in (CAP_TPU_SHA_UNROLL=1) only.

    Measured on-chip (round 5): unrolling did NOT beat the scan inside
    the PSS program (86 vs 74 ms/16k — the scan was never the binding
    term) and costs minutes of XLA compile per call site. Kept as a
    tested experiment flag; the scan is the default everywhere.
    """
    import os

    return os.environ.get("CAP_TPU_SHA_UNROLL") in ("1", "true", "yes")


def _round_ops(t, a, b, c, d, e, f, g, h, w_t, kt):
    s1 = _ror(e, 6) ^ _ror(e, 11) ^ _ror(e, 25)
    ch = (e & f) ^ (~e & g)
    t1 = h + s1 + ch + kt + w_t
    s0 = _ror(a, 2) ^ _ror(a, 13) ^ _ror(a, 22)
    maj = (a & b) ^ (a & c) ^ (b & c)
    t2 = s0 + maj
    return (t1 + t2, a, b, c, d + t1, e, f, g)


def _compress_unrolled(state, words):
    """compress() with the 64 rounds as one fused op chain."""
    w = [words[i] for i in range(16)]
    s = tuple(state)
    for t in range(64):
        if t >= 16:
            ws0 = _ror(w[t - 15], 7) ^ _ror(w[t - 15], 18) ^ \
                (w[t - 15] >> 3)
            ws1 = _ror(w[t - 2], 17) ^ _ror(w[t - 2], 19) ^ \
                (w[t - 2] >> 10)
            w.append(w[t - 16] + ws0 + w[t - 7] + ws1)
        s = _round_ops(t, *s, w[t], jnp.uint32(_K[t]))
    return tuple(a + b for a, b in zip(state, s))


def compress(state, words):
    """One SHA-256 compression over the batch.

    state: tuple of 8 [N] uint32; words: [16, N] uint32 message words.
    Returns the new 8-tuple. uint32 adds wrap, matching the spec.

    Default everywhere: a lax.scan with a rolling 16-word schedule
    window (W[t+16] = W[t] + σ0(W[t+1]) + W[t+9] + σ1(W[t+14])).
    CAP_TPU_SHA_UNROLL=1 opts into the fully unrolled rounds — see
    _unrolled for why that experiment stays off.
    """
    from jax import lax

    if _unrolled():
        return _compress_unrolled(state, words)

    k_arr = jnp.asarray(_K)

    def round_body(carry, kt):
        (a, b, c, d, e, f, g, h), w_win = carry
        w_t = w_win[0]
        s1 = _ror(e, 6) ^ _ror(e, 11) ^ _ror(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kt + w_t
        s0 = _ror(a, 2) ^ _ror(a, 13) ^ _ror(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        nxt = (t1 + t2, a, b, c, d + t1, e, f, g)
        # schedule: W[t+16] from the current window (extra entries past
        # round 48 are computed and discarded — cheaper than a branch)
        ws0 = _ror(w_win[1], 7) ^ _ror(w_win[1], 18) ^ (w_win[1] >> 3)
        ws1 = _ror(w_win[14], 17) ^ _ror(w_win[14], 19) ^ \
            (w_win[14] >> 10)
        w_new = w_win[0] + ws0 + w_win[9] + ws1
        w_win = jnp.concatenate([w_win[1:], w_new[None]], axis=0)
        return (nxt, w_win), None

    (out, _), _ = lax.scan(round_body, (tuple(state), words), k_arr)
    return tuple(s + v for s, v in zip(state, out))


def _bytes_to_words(block):
    """[N, 64] uint8 → [16, N] uint32 big-endian words."""
    b = block.astype(jnp.uint32).reshape(block.shape[0], 16, 4)
    w = (b[:, :, 0] << 24) | (b[:, :, 1] << 16) | \
        (b[:, :, 2] << 8) | b[:, :, 3]
    return w.T


def _init_state(n):
    return tuple(jnp.full((n,), int(v), jnp.uint32) for v in _H0)


def sha256_fixed(msgs):
    """SHA-256 of [N, L] uint8 messages, fixed L ≤ 55 (single block).

    Returns [N, 32] uint8 digests. The MGF1 seeds (h_len + 4 bytes) and
    other short fixed-size inputs take this path.
    """
    n, length = msgs.shape
    assert length <= 55, "single-block limit"
    block = jnp.zeros((n, 64), jnp.uint8)
    block = block.at[:, :length].set(msgs)
    block = block.at[:, length].set(jnp.uint8(0x80))
    bits = length * 8
    block = block.at[:, 62].set(jnp.uint8(bits >> 8))
    block = block.at[:, 63].set(jnp.uint8(bits & 0xFF))
    state = compress(_init_state(n), _bytes_to_words(block))
    return _digest_bytes(state)


def sha256_var(msgs, lens, max_len: int):
    """SHA-256 of [N, max_len] uint8 buffers with per-token ``lens``.

    Bytes at and beyond each token's length MUST already be zero (the
    padding 0x80 and the 64-bit bit-length are placed per token here).
    Runs ceil((max_len + 9) / 64) compressions and snapshots each
    token's state after its own final block. Returns [N, 32] uint8.
    """
    n = msgs.shape[0]
    n_blocks = (max_len + 9 + 63) // 64
    buf = jnp.zeros((n, n_blocks * 64), jnp.uint8)
    buf = buf.at[:, :msgs.shape[1]].set(msgs)
    pos = jnp.arange(n_blocks * 64, dtype=jnp.int32)[None, :]
    lens32 = lens.astype(jnp.int32)[:, None]
    buf = jnp.where(pos == lens32, jnp.uint8(0x80), buf)
    # 64-bit big-endian bit length in the last 8 bytes of each token's
    # final block (lens < 2^28 here, so 4 low bytes suffice; the rest
    # stay zero).
    final_block = (lens32 + 8) // 64      # block index holding length
    msg_bits = (lens.astype(jnp.uint32) * 8)[:, None]
    len_base = final_block * 64 + 56
    for j in range(4):                    # bytes 60..63 of that block
        shift = jnp.uint32(8 * (3 - j))
        byte = ((msg_bits >> shift) & 0xFF).astype(jnp.uint8)
        buf = jnp.where(pos == len_base + 60 - 56 + j, byte, buf)

    state = _init_state(n)
    out = state
    for i in range(n_blocks):
        state = compress(state,
                         _bytes_to_words(buf[:, i * 64:(i + 1) * 64]))
        is_final = (final_block[:, 0] == i)
        out = tuple(jnp.where(is_final, s, o)
                    for s, o in zip(state, out))
    return _digest_bytes(out)


def _digest_bytes(state):
    """8×[N] uint32 state → [N, 32] uint8 big-endian digest."""
    cols = []
    for s in state:
        cols.append((s >> 24).astype(jnp.uint8))
        cols.append(((s >> 16) & 0xFF).astype(jnp.uint8))
        cols.append(((s >> 8) & 0xFF).astype(jnp.uint8))
        cols.append((s & 0xFF).astype(jnp.uint8))
    return jnp.stack(cols, axis=1)
