"""Fused RNS Montgomery reduction (REDC) as a Pallas TPU kernel.

The EC/Ed ladders spend most of their device time in ``rns._redc``:
two base extensions (packed bf16 matmuls) glued by ~10 elementwise
Barrett-fix passes over [I, N] i32 residue planes. Under plain XLA each
matmul boundary materializes its neighborhood to HBM, so the chain is
HBM-traffic-bound (docs/PERF.md: the 160-layer ladder chain measures
~0.4 ms/layer at N=65536 while its FLOPs are microseconds).

This kernel runs the whole REDC — channel products, σ, A→B extension,
the B-side multiplies, and the B→A extension — on VMEM-resident tiles,
touching HBM once for inputs and once for outputs. Serves per-channel
(EC/Ed) contexts, default ON for TPU backends since the round-4 A/B
(CAP_TPU_PALLAS=0/1 overrides; numbers in docs/PERF.md). The RSA REDC
(per-token key constants) stays on the XLA path.

Numerical contract: identical to rns._redc. The Barrett quotient
guess is within ±1 of floor(v/m) for v < 2^31 (see _fix), and the two
conditional corrections consume exactly that margin — deriving 1/m in
f32 in-kernel (vs the host's f64→f32 constant) adds ≤ 2^-24 relative
error, already inside the ±1 analysis. There is NO spare quotient
slack: any new operation that widens v past 2^31 needs its own bound.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict

import numpy as np

import jax
import jax.numpy as jnp

I32 = jnp.int32
F32 = jnp.float32
BF16 = jnp.bfloat16

_TILE = 2048        # lanes per grid step (multiple of 128)


def enabled() -> bool:
    """Fused Pallas REDC: CAP_TPU_PALLAS=1/0 overrides.

    Default ON for the TPU backend only — a GPU backend keeps the XLA
    path, like pallas_madd (round-4 A/B, resident packed paths @16k,
    min-of-3: EdDSA 549→602k/s, ES384 268→321k/s, ES256 608→618k/s,
    ES512 neutral — the non-madd REDCs in the EC/Ed ladders, batch
    inversion, and accumulator merge all ride it). CPU defaults to the
    XLA path (the parity reference); setting CAP_TPU_PALLAS=1 on CPU
    runs the kernel in interpret mode, which the parity tests use.
    """
    v = os.environ.get("CAP_TPU_PALLAS")
    if v is not None:
        return v not in ("0", "false", "no")
    return jax.default_backend() == "tpu"


def _fix(v, m, inv_f):
    """Exact v mod m for 0 <= v < 2^31 (rns._mod_fix).

    ONE correction each way suffices: the f32 quotient guess is within
    ±1 of floor(v/m) — |f32(v) − v| ≤ ulp(2^31)/2 = 128 contributes
    ≤ 128/m ≤ 2^-5 after ×(1/m) (m ≥ 2^12), the 1/m rounding
    ≤ (v/m)·2^-24 ≤ 2^-5, and the product rounding ≤ ulp(2^19)/2
    = 2^-5 — total ≤ 0.094 < 1, so r = v − q·m lands in (−m, 2m).
    """
    q = jnp.floor(v.astype(F32) * inv_f).astype(I32)
    r = v - q * m
    r = jnp.where(r < 0, r + m, r)
    r = jnp.where(r >= m, r - m, r)
    return r


def _extend_in_kernel(sig, inv_src_f, w_blk, m_dst, inv_dst_f,
                      src_prod_mod_dst, offset, c14):
    """rns._extend on VMEM tiles: [I_src, T] -> [I_dst, T].

    The hi/lo 7-bit product split rides the M and K matmul axes, not
    the lane axis: w_blk is the host-built block matrix
    ``[[wh, 0], [0, wl], [wl, wh]]`` of shape [3J, 2I], multiplied by
    ``[sig>>7 ; sig&127]`` [2I, T] — one pass of N=T lanes instead of
    two passes of the old [2J, I] @ [I, 2T] layout. On the 128×128 MXU
    both layouts fit one M·K block for every curve context (3J ≤ 135
    padded, 2I ≤ 92 for P-521), so halving N halves the MXU unit
    count outright; every product term stays 127·127 and every f32
    accumulation < 2^20, bit-identical to the two-pass form.

    Recombination bounds (EC/Ed contexts: I ≤ ~45 channels of 13-bit
    primes): hh/mid/ll ≤ 2I·127² < 2^20; 2^7 mod m = 128 EXACTLY
    (m ≥ 2^12), so mid·128 + ll < 2^28 needs no per-term fixes; only
    hh (weight 2^14 > m) reduces first. α ∈ [-1, I_src], so its mod-m
    adjust is one select, not an integer division; c14 = 2^14 mod m
    arrives as a host constant.
    """
    # Structural overflow guard (shapes are static at trace time):
    # fix(hh)·c14 + mid·128 + ll < 2^28 + I·16129·257 stays below 2^31
    # only for I ≤ 448 — ample for per-channel contexts (P-521 ≈ 45),
    # but any future reuse beyond that must restore per-term fixes.
    assert sig.shape[0] <= 448, "extension recombination would overflow"
    j = w_blk.shape[0] // 3
    x_blk = jnp.concatenate(
        [(sig >> 7).astype(BF16), (sig & 127).astype(BF16)], axis=0)
    c = jnp.dot(w_blk, x_blk, preferred_element_type=F32).astype(I32)
    hh = c[:j]
    ll = c[j:2 * j]
    mid = c[2 * j:]
    alpha = jnp.floor(
        jnp.sum(sig.astype(F32) * inv_src_f, axis=0, keepdims=True)
        + offset).astype(I32)                              # [1, T]
    comb = _fix(_fix(hh, m_dst, inv_dst_f) * c14
                + mid * 128 + ll, m_dst, inv_dst_f)
    alpha_adj = jnp.where(alpha < 0, alpha + m_dst, alpha)
    corr = _fix(alpha_adj * src_prod_mod_dst, m_dst, inv_dst_f)
    # comb, corr < m → comb − corr + m ∈ (0, 2m): one conditional
    # subtract replaces the full Barrett pass (same result exactly).
    r = comb - corr + m_dst
    return jnp.where(r >= m_dst, r - m_dst, r)


def make_rns_ops(mA, mB, sigc, nB, wab, wba,
                 amodb, bmoda, invab, invmib, cpA, cpB, c14a, c14b):
    """In-kernel RNS field-op closures over VALUE arrays.

    One implementation of the REDC (both base extensions) and the lazy
    add/sub discipline, shared by the fused mixed-add (pallas_madd)
    and the fused Edwards-add (pallas_edw) kernels — their numerics
    cannot diverge from each other or from this module's REDC kernel.
    wab/wba are the [3J, 2I] extension block matrices (see
    _extend_in_kernel); cpA/cpB are [I, maxc] PRE-TRANSPOSED (static
    2-D slices only: int indexing lowers to a gather Mosaic rejects).
    Returns (fixA, fixB, rmul, radd, rsub, rfix) on (A, B)
    residue-plane pairs.
    """
    invA_f = 1.0 / mA.astype(F32)
    invB_f = 1.0 / mB.astype(F32)

    def fixA(v):
        return _fix(v, mA, invA_f)

    def fixB(v):
        return _fix(v, mB, invB_f)

    def redc(pA, pB):
        sig = fixA(pA * sigc)
        q_B = _extend_in_kernel(sig, invA_f, wab,
                                mB, invB_f, amodb, -1e-4, c14b)
        # q·p + x < 2^28 — one fix covers the merged product-and-add
        t_B = fixB(pB + q_B * nB)
        t_B = fixB(t_B * invab)
        sig2 = fixB(t_B * invmib)
        t_A = _extend_in_kernel(sig2, invB_f, wba,
                                mA, invA_f, bmoda, 0.5 - 1e-4, c14a)
        return t_A, t_B

    def rmul(a, b):
        return redc(fixA(a[0] * b[0]), fixB(a[1] * b[1]))

    def radd(a, b):
        return (a[0] + b[0], a[1] + b[1])

    def rsub(a, b, cmul: int, guard: int):
        # a + cmul·p − b + guard·m: mirrors ec_rns.rsub's value/digit
        # bound discipline exactly (bounds documented there).
        ga = guard * mA
        gb = guard * mB
        return (a[0] + cpA[:, cmul:cmul + 1] - b[0] + ga,
                a[1] + cpB[:, cmul:cmul + 1] - b[1] + gb)

    def rfix(a):
        return (fixA(a[0]), fixB(a[1]))

    return fixA, fixB, rmul, radd, rsub, rfix


def _redc_kernel(xA_ref, xB_ref, mA_ref, mB_ref, sigc_ref, nB_ref,
                 wab_ref, wba_ref,
                 amodb_ref, bmoda_ref, invab_ref, invmib_ref,
                 c14a_ref, c14b_ref,
                 tA_ref, tB_ref):
    xA = xA_ref[:]
    xB = xB_ref[:]
    mA = mA_ref[:]                       # [IA, 1] i32
    mB = mB_ref[:]                       # [IB, 1] i32
    invA_f = 1.0 / mA.astype(F32)
    invB_f = 1.0 / mB.astype(F32)

    sig = _fix(xA * sigc_ref[:], mA, invA_f)
    q_B = _extend_in_kernel(sig, invA_f, wab_ref[:],
                            mB, invB_f, amodb_ref[:], -1e-4,
                            c14b_ref[:])
    # q·n + x < 2^28 — one fix covers the merged product-and-add
    t_B = _fix(xB + q_B * nB_ref[:], mB, invB_f)
    t_B = _fix(t_B * invab_ref[:], mB, invB_f)
    sig2 = _fix(t_B * invmib_ref[:], mB, invB_f)
    t_A = _extend_in_kernel(sig2, invB_f, wba_ref[:],
                            mA, invA_f, bmoda_ref[:], 0.5 - 1e-4,
                            c14a_ref[:])
    tA_ref[:] = t_A
    tB_ref[:] = t_B


_CONST_CACHE: Dict[int, tuple] = {}


def pinned_ctx_cache(cache: Dict[int, tuple], c, build):
    """id(c)-keyed constant cache whose value pins the context object.

    Pinning is the whole fix: while the entry holds `c`, its id cannot
    be recycled, so a hit is always for the right context. (Contexts
    are module-level singletons in practice, so growth is bounded.)
    """
    hit = cache.get(id(c))
    if hit is not None:
        return hit[1]
    out = build()
    cache[id(c)] = (c, out)
    return out


def _ctx_consts(c) -> tuple:
    """Per-context 2-D constant arrays for the kernel (cached)."""
    return pinned_ctx_cache(_CONST_CACHE, c, lambda: _build_consts(c))


def _w_block(pair):
    """(Wh, Wl) [J, I] halves → the [3J, 2I] extension block matrix
    ``[[Wh, 0], [0, Wl], [Wl, Wh]]`` (see _extend_in_kernel). Entries
    stay 7-bit, so bf16 is exact. HOST numpy (ml_dtypes bf16): this
    feeds the pinned const caches, which must never hold JAX arrays —
    one created inside a jit trace leaks that trace."""
    import ml_dtypes

    wh = np.asarray(pair[0], np.float32)
    wl = np.asarray(pair[1], np.float32)
    j, i = wh.shape
    out = np.zeros((3 * j, 2 * i), np.float32)
    out[:j, :i] = wh
    out[j:2 * j, i:] = wl
    out[2 * j:, :i] = wl
    out[2 * j:, i:] = wh
    return out.astype(ml_dtypes.bfloat16)


def _build_consts(c) -> tuple:
    (dA, dB, w_ab, w_ba, Amod_B, Bmod_A, invA_B) = c.consts

    def col(v):
        # numpy on host: redc_fused runs inside jit traces, and
        # tracer-created arrays must never be cached (they leak);
        # numpy constants embed safely into every trace.
        return np.asarray(v, np.int32).reshape(-1, 1)

    return (
        col(dA["m"]), col(dB["m"]), col(c.sig_c), col(c.p_B),
        _w_block(w_ab), _w_block(w_ba),
        col(Amod_B), col(Bmod_A), col(invA_B), col(dB["inv_Mi"]),
        col((1 << 14) % np.asarray(c.A.m, np.int64)),
        col((1 << 14) % np.asarray(c.B.m, np.int64)),
    )


@partial(jax.jit, static_argnames=("ia", "ib", "interpret"))
def _redc_call(xA, xB, mA, mB, sigc, nB, wab, wba,
               amodb, bmoda, invab, invmib, c14a, c14b,
               ia: int, ib: int, interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = xA.shape[1]
    grid = n // _TILE

    def col_spec(rows):
        return pl.BlockSpec((rows, _TILE), lambda i: (0, i),
                            memory_space=pltpu.VMEM)

    def const_spec(shape):
        return pl.BlockSpec(shape, lambda i: tuple(0 for _ in shape),
                            memory_space=pltpu.VMEM)

    consts = (mA, mB, sigc, nB, wab, wba, amodb, bmoda,
              invab, invmib, c14a, c14b)
    return pl.pallas_call(
        _redc_kernel,
        out_shape=(jax.ShapeDtypeStruct((ia, n), I32),
                   jax.ShapeDtypeStruct((ib, n), I32)),
        grid=(grid,),
        in_specs=[col_spec(ia), col_spec(ib)]
        + [const_spec(a.shape) for a in consts],
        out_specs=(col_spec(ia), col_spec(ib)),
        interpret=interpret,
    )(xA, xB, *consts)


def redc_fused(c, x_A, x_B):
    """Drop-in for rns._redc on per-channel (EC/Ed) contexts.

    Pads the lane axis to the tile size; padding lanes hold zeros,
    which every fix maps to a valid residue and the caller's slices
    drop.
    """
    ia, ib = x_A.shape[0], x_B.shape[0]
    n = x_A.shape[1]
    pad = (-n) % _TILE
    if pad:
        x_A = jnp.pad(x_A, ((0, 0), (0, pad)))
        x_B = jnp.pad(x_B, ((0, 0), (0, pad)))
    tA, tB = _redc_call(x_A, x_B, *_ctx_consts(c), ia=ia, ib=ib,
                        interpret=jax.default_backend() == "cpu")
    if pad:
        tA = tA[:, :n]
        tB = tB[:, :n]
    return tA, tB
