"""Ed25519 point arithmetic in RNS form (MXU path).

Same design as ``ec_rns``: the extended-Edwards ladder runs on
carry-free residue pairs (complete a = -1 mixed additions, 7 rmuls
each, window tables as A-domain residues including identity rows at
digit 0 — no masks, no infinity lane needed). The finish converts
(X, Y, Z) back to 16-bit limbs via CRT reconstruction
(``rns.RNSToLimbs``) and reuses the limb engine's batched inversion +
encoding comparison, which needs canonical bytes (x's parity is not a
residue-domain property).

Value bounds: every rmul output < 3p; sums grow to ≤ 10p between
multiplies; A ≥ 2^14·p keeps λ₁λ₂p²/A ≪ p (max product pair 10·9).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import limbs as L
from .ec_rns import radd, rmul, rsel, rsub
from .ed25519 import (
    _B_POINT,
    _IDENTITY,
    _edw_add,
    K,
    L_ORDER,
    NBITS,
    P,
    consts,
)

W_BITS = 8                          # byte-aligned window digits
NW8 = (NBITS + W_BITS - 1) // W_BITS  # 32 windows
PER = 1 << W_BITS                   # 256 entries incl. identity at d=0

from .rns import FieldRNSContext, I32  # noqa: E402


class Ed25519RNSContext(FieldRNSContext):
    """Field context for p = 2^255−19 (shared FieldRNSContext build)."""

    def __init__(self):
        super().__init__(P, K)      # to_limbs k_out = K+1 (< 3p < 2^257)


_CTX: Optional[Ed25519RNSContext] = None


def ctx() -> Ed25519RNSContext:
    global _CTX
    if _CTX is None:
        _CTX = Ed25519RNSContext()
    return _CTX


def _one_dom(c: Ed25519RNSContext):
    one = c.a_mod_p
    return (jnp.asarray([one % int(m) for m in c.A.m], I32)[:, None],
            jnp.asarray([one % int(m) for m in c.B.m], I32)[:, None])


def _edw_madd_rns(c, X, Y, Z, T, ym, yp, t2):
    """Complete mixed addition, RNS pairs. State bounds < 3p in/out.

    7 field multiplies in 2 batched REDC dispatches (layer merge).
    """
    from .ec_rns import rmul_many

    # Lazy digit bounds (units of m): state ≤ m in; products ≤ 12m².
    a, b, cc = rmul_many(
        c, [(rsub(c, Y, X, 4, guard=1), ym),
            (radd(c, Y, X), yp), (T, t2)])
    d = radd(c, Z, Z)                        # ≤ 2m
    e = rsub(c, b, a, 4, guard=1)            # ≤ 3m
    f = rsub(c, d, cc, 4, guard=1)           # ≤ 4m
    g = radd(c, d, cc)                       # ≤ 3m
    h = radd(c, b, a)                        # ≤ 2m
    return tuple(rmul_many(c, [(e, f), (g, h), (f, g), (e, h)]))


def _twod_dom(c: "Ed25519RNSContext"):
    """A-domain residue columns of the curve constant 2d."""
    from .ed25519 import D_CONST

    v = 2 * D_CONST % P * c.a_mod_p % P
    return (jnp.asarray([v % int(m) for m in c.A.m], I32)[:, None],
            jnp.asarray([v % int(m) for m in c.B.m], I32)[:, None])


def _edw_add_rns(c, P1, P2, twod):
    """Complete full extended + extended addition (add-2008-hwcd-3,
    a = -1), RNS pairs. Runs ONCE per batch to merge the two ladder
    accumulators. Inputs < 3p with canonical digits; outputs likewise.
    """
    from .ec_rns import rmul_many

    X1, Y1, Z1, T1 = P1
    X2, Y2, Z2, T2 = P2
    a, b, t12, z12 = rmul_many(
        c, [(rsub(c, Y1, X1, 4, guard=1), rsub(c, Y2, X2, 4, guard=1)),
            (radd(c, Y1, X1), radd(c, Y2, X2)),
            (T1, T2), (Z1, Z2)])             # λ ≤ 49, ≤ 9m² → < 3p, ≤ m
    cc = rmul(c, t12, twod)                  # < 3p, ≤ m
    d = radd(c, z12, z12)                    # < 6p, ≤ 2m
    e = rsub(c, b, a, 4, guard=1)            # < 7p, ≤ 3m
    f = rsub(c, d, cc, 4, guard=1)           # < 10p, ≤ 4m
    g = radd(c, d, cc)                       # < 9p, ≤ 3m
    h = radd(c, b, a)                        # < 6p, ≤ 2m
    return tuple(rmul_many(c, [(e, f), (g, h), (f, g), (e, h)]))


def _window_triple_residue_rows(c: Ed25519RNSContext,
                                pt: Tuple[int, int]) -> np.ndarray:
    """[3, NW·16, I_A+I_B] A-domain triples of d·2^{4i}·pt (d=0: id)."""
    nw = NW8
    ia, ib = c.A.count, c.B.count
    rows = np.empty((3, nw * PER, ia + ib), np.int32)
    am = c.a_mod_p
    base = pt
    for i in range(nw):
        acc = _IDENTITY
        for d in range(PER):
            if d:
                acc = _edw_add(acc, base)
            x, y = acc
            vals = ((y - x) % P, (y + x) % P, _t2_of(x, y))
            for t, v in enumerate(vals):
                rows[t, i * PER + d] = c.residues_of(v * am % P)
        for _ in range(W_BITS):
            base = _edw_add(base, base)
    return rows


def _t2_of(x: int, y: int) -> int:
    from .ed25519 import D_CONST

    return 2 * D_CONST * x % P * y % P


_B_TABLE_RNS = None


def b_table_rns():
    global _B_TABLE_RNS
    if _B_TABLE_RNS is None:
        rows = _window_triple_residue_rows(ctx(), _B_POINT)
        _B_TABLE_RNS = tuple(jnp.asarray(rows[t]) for t in range(3))
    return _B_TABLE_RNS


class Ed25519RNSKeyTable:
    """Per-key window tables of -A as A-domain residue triples."""

    def __init__(self, keys_decoded):
        """keys_decoded: list of (x, y) affine points or None (invalid),
        matching Ed25519KeyTable's decode results."""
        c = ctx()
        nk = len(keys_decoded)
        rows = NW8 * PER
        ia, ib = c.A.count, c.B.count
        ta = np.empty((3, nk * rows, ia + ib), np.int32)
        for i, a in enumerate(keys_decoded):
            neg_a = _IDENTITY if a is None else ((P - a[0]) % P, a[1])
            ta[:, i * rows:(i + 1) * rows] = \
                _window_triple_residue_rows(c, neg_a)
        self.tna = tuple(jnp.asarray(ta[t]) for t in range(3))


@jax.jit
def _ed25519_rns_core(s, kk, yr, sign_r, bad_key, key_idx,
                      ta_ym, ta_yp, ta_t2, tb_ym, tb_yp, tb_t2,
                      p, pp, pr2, pone, pm2, l_):
    """Ed25519 verify: RNS ladder + limb-domain finish.

    Same contract as ed25519._ed25519_core; tables are RNS residue
    rows [·, I_A + I_B].
    """
    from . import bignum as B

    c = ctx()
    shape = s.shape
    k = shape[0]
    p1, pp1, pr21, pone1, pm21 = p, pp, pr2, pone, pm2
    pb = jnp.broadcast_to(p, shape)
    ppb = jnp.broadcast_to(pp, shape)
    l_b = jnp.broadcast_to(l_, shape)

    s_ok = ~B.compare_ge(s, l_b)

    def bytes_of(u):
        return jnp.stack(
            [(u >> (8 * j)) & 255 for j in range(2)], axis=1
        ).reshape(2 * k, shape[1]).astype(jnp.int32)

    dig1 = bytes_of(s)
    dig2 = bytes_of(kk)
    key_base = key_idx.astype(jnp.int32) * (NW8 * PER)

    ia = c.A.count
    n_tok = shape[1]

    def gather3(ta, tb, tc, idx):
        g = [jnp.take(t, idx, axis=0).T for t in (ta, tb, tc)]
        return [(v[:ia], v[ia:]) for v in g]

    # TWO-ACCUMULATOR ladder (see ec_rns._ecdsa_rns_core): the B-chain
    # ([S]B) and A-chain ([k](−A)) additions are independent, so both
    # run as ONE complete mixed-add over [I, 2N] lanes — the same 2
    # REDC layers per window serve both chains. One full Edwards add
    # merges the accumulators (complete formulas: no flags needed).
    one_d = _one_dom(c)
    zA = jnp.zeros((c.A.count, 2 * n_tok), I32)
    zB = jnp.zeros((c.B.count, 2 * n_tok), I32)
    one_b = (jnp.broadcast_to(one_d[0], zA.shape),
             jnp.broadcast_to(one_d[1], zB.shape))
    X = (zA, zB)
    Y = one_b
    Z = one_b
    T = (zA, zB)

    cat_ym = jnp.concatenate([tb_ym, ta_ym], axis=0)
    cat_yp = jnp.concatenate([tb_yp, ta_yp], axis=0)
    cat_t2 = jnp.concatenate([tb_t2, ta_t2], axis=0)
    q_off = tb_ym.shape[0]

    from . import pallas_edw

    use_fused = pallas_edw.enabled()
    interp = jax.default_backend() == "cpu"   # interpret mode on CPU

    def ladder_body(i, state):
        X, Y, Z, T = state
        d1 = lax.dynamic_slice_in_dim(dig1, i, 1, axis=0)[0]
        d2 = lax.dynamic_slice_in_dim(dig2, i, 1, axis=0)[0]
        idx = jnp.concatenate(
            [i * PER + d1, q_off + key_base + i * PER + d2])
        ym, yp, t2 = gather3(cat_ym, cat_yp, cat_t2, idx)
        if use_fused:
            # One VMEM-resident kernel for the whole mixed-add
            # (pallas_edw; bit-identical to _edw_madd_rns).
            return pallas_edw.edw_madd_fused(c, X, Y, Z, T, ym, yp, t2,
                                             interpret=interp)
        return _edw_madd_rns(c, X, Y, Z, T, ym, yp, t2)

    X, Y, Z, T = lax.fori_loop(0, NW8, ladder_body, (X, Y, Z, T))

    def halves(pair):
        return ((pair[0][:, :n_tok], pair[1][:, :n_tok]),
                (pair[0][:, n_tok:], pair[1][:, n_tok:]))

    Xb, Xa = halves(X)
    Yb, Ya = halves(Y)
    Zb, Za = halves(Z)
    Tb, Ta = halves(T)
    X, Y, Z, T = _edw_add_rns(c, (Xb, Yb, Zb, Tb), (Xa, Ya, Za, Ta),
                              _twod_dom(c))

    # RNS → limbs, canonicalize mod p, then the limb-domain finish.
    def to_canonical(v_pair):
        v = c.to_limbs(v_pair[0])               # [17, N], value < 3p
        p_pad = jnp.concatenate(
            [jnp.broadcast_to(p1, (k, n_tok)),
             jnp.zeros((1, n_tok), jnp.uint32)], axis=0)
        for _ in range(2):
            v = B.sub_where(v, p_pad, B.compare_ge(v, p_pad))
        return v[:k]

    Xl = to_canonical(X)
    Yl = to_canonical(Y)
    Zl = to_canonical(Z)

    z_m = B.mont_mul(Zl, jnp.broadcast_to(pr2, shape), pb, ppb)
    zinv = B.batch_mont_inverse(z_m, p1, pp1, pr21, pone1, pm21,
                                nbits=255)
    # x = X·(z⁻¹·R)·R⁻¹ etc: one montmul cancels the R factor.
    x = B.mont_mul(Xl, zinv, pb, ppb)
    y = B.mont_mul(Yl, zinv, pb, ppb)

    enc_ok = jnp.all(y == yr, axis=0) & ((x[0] & 1) == sign_r)
    return s_ok & enc_ok & ~bad_key
