"""ECDSA point arithmetic in residue-number-system form (MXU path).

The limb-based EC engine (``ec``) spends its time in carry
normalization, compares, and borrow scans around every field multiply.
In RNS form (same machinery as ``rns`` — two bases of ~13-bit primes):

- field multiply = per-channel products + one Bajard/Kawamura REDC
  whose base extensions are fixed-matrix matmuls;
- field add/sub = pure per-channel modular add/sub — NO carries, NO
  compares, NO scans anywhere in the ladder;
- values are "A-domain" residue pairs x̃ = x·A mod p held as
  (xA [I_A, N], xB [I_B, N]); bounds are tracked statically: every
  rmul output is < 3p, sums/differences grow to ≤ ~16p between
  multiplies, and A ≥ 2^14·p keeps every product's λ₁λ₂p²/A term
  far below p (the stability condition);
- the point at infinity is an explicit boolean lane (not a Z = 0
  sentinel), so the ladder needs no residue zero-tests;
- equality tests (final projective check, same-x degeneracy flags)
  use the multiple-of-p trick: d = x + c₀p − y is ≡ 0 (mod p) iff d
  equals one of a handful of precomputed c·p residue vectors — exact,
  since d ≪ prod(A).

Scalar-field work (s⁻¹ batch inversion, u1/u2, range checks) stays in
the limb engine — it is a tiny fraction of the cost and the window
digits need limb form anyway. Replaces crypto/ecdsa.Verify's hot loop
(reference: jwt/keyset.go:126-139 → Go stdlib) on accelerator
backends; bit-exact parity enforced by the shared conformance tests.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import limbs as L
from .ec import CurveParams, ECKeyTable, curve
from .rns import (
    FieldRNSContext,
    I32,
    _mod_fix,
    _redc,
)


def default_w_bits() -> int:
    """Interleaved-window width for the RNS EC ladder.

    8-bit everywhere: measured on the attached chip, 12-bit windows
    (22 ladder steps instead of 32) are 2.3× SLOWER — the 2^12-entry
    tables (~130 MB at 8 keys) push the per-window gathers into
    scattered HBM reads, which dominates the saved REDC depth. The
    machinery supports any width (CAP_TPU_EC_WBITS to re-measure on
    other parts); docs/PERF.md records the A/B.
    """
    import os

    v = os.environ.get("CAP_TPU_EC_WBITS")
    if v:
        return int(v)
    return 8


class ECRNSContext(FieldRNSContext):
    """Per-curve field context (shared construction in FieldRNSContext)."""

    def __init__(self, cp: CurveParams, w_bits: int):
        super().__init__(cp.p, cp.k)
        self.cp = cp
        self.w_bits = w_bits
        self.n_windows = (cp.nbits + w_bits - 1) // w_bits


_CTX: Dict[tuple, ECRNSContext] = {}


def ctx_for(crv: str, w_bits: Optional[int] = None) -> ECRNSContext:
    if w_bits is None:
        w_bits = default_w_bits()
    key = (crv, w_bits)
    if key not in _CTX:
        _CTX[key] = ECRNSContext(curve(crv), w_bits)
    return _CTX[key]


# ---------------------------------------------------------------------------
# Field ops on (xA, xB) residue pairs
# ---------------------------------------------------------------------------

def _fixA(c, x):
    return _mod_fix(x, c.dA["m"][:, None], c.dA["inv_f"][:, None])


def _fixB(c, x):
    return _mod_fix(x, c.dB["m"][:, None], c.dB["inv_f"][:, None])


def _redc_dispatch(c: ECRNSContext, pA, pB):
    """REDC via the fused Pallas kernel on accelerators, XLA otherwise."""
    from . import pallas_redc

    if pallas_redc.enabled():
        return pallas_redc.redc_fused(c, pA, pB)
    return _redc(pA, pB, c.sig_c, c.p_B, c.consts)


def rmul(c: ECRNSContext, a, b):
    """(a·b)·A⁻¹ mod p — output value < 3p for λ₁λ₂ ≤ 2^14."""
    pA = _fixA(c, a[0] * b[0])
    pB = _fixB(c, a[1] * b[1])
    return _redc_dispatch(c, pA, pB)


def rmul_many(c: ECRNSContext, pairs):
    """Batch independent rmuls through ONE REDC (concat along batch).

    The base-extension matmuls and channel fixes are shape-agnostic,
    so k independent multiplies cost one dispatch over [I, k·N] —
    bigger matmuls, fewer kernel launches.
    """
    n = pairs[0][0][0].shape[1]
    pA = _fixA(c, jnp.concatenate([a[0] * b[0] for a, b in pairs],
                                  axis=1))
    pB = _fixB(c, jnp.concatenate([a[1] * b[1] for a, b in pairs],
                                  axis=1))
    tA, tB = _redc_dispatch(c, pA, pB)
    return [(tA[:, i * n:(i + 1) * n], tB[:, i * n:(i + 1) * n])
            for i in range(len(pairs))]


def radd(c: ECRNSContext, a, b):
    """a + b — LAZY: digits grow (c₁+c₂)·m, no Barrett fix.

    Safe because channel moduli are < 2^13 and ``rmul`` fixes its
    products (which stay < 2^31 while the digit-growth product
    c₁c₂ ≤ 32 — the ladder's worst pair is far below that).
    """
    return (a[0] + b[0], a[1] + b[1])


def rsub(c: ECRNSContext, a, b, cmul: int, guard: int = 4):
    """a + cmul·p − b — LAZY (no fix). cmul·p must dominate b's VALUE
    bound; ``guard``·m must dominate b's DIGIT bound."""
    ga = guard * c.dA["m"][:, None]
    gb = guard * c.dB["m"][:, None]
    return (a[0] + c.cp_A[cmul][:, None] - b[0] + ga,
            a[1] + c.cp_B[cmul][:, None] - b[1] + gb)


def rfix(c: ECRNSContext, x):
    """Canonicalize digits (< m) of a lazily-grown pair."""
    return (_fixA(c, x[0]), _fixB(c, x[1]))


def rsel(mask, a, b):
    """where(mask) per pair."""
    m = mask[None, :]
    return (jnp.where(m, a[0], b[0]), jnp.where(m, a[1], b[1]))


def congruent_zero(c: ECRNSContext, x, max_c: int):
    """[N] bool: value(x) ≡ 0 (mod p), for values < max_c·p.

    Base A alone decides: every value in play is ≪ prod(A), so its
    A-residues determine it uniquely — no need to compare base B.
    Accepts lazily-grown digits (fixes internally before comparing).
    """
    xa = _fixA(c, x[0])
    ok = jnp.zeros(xa.shape[1], bool)
    for cc in range(max_c):
        ok = ok | jnp.all(xa == c.cp_A[cc][:, None], axis=0)
    return ok


def congruent_zero_probe(c: ECRNSContext, x, max_c: int, nch: int = 2):
    """[N] bool: SUFFICIENT test for value(x) ≡ 0 (mod p) on ``nch``
    probe channels only — every true congruence is caught (residues of
    a multiple of p match c·p on all channels, hence on the probe
    subset), with ~max_c/(m₀·m₁) ≈ 3e-7 false positives.

    Used for the per-window degeneracy flags, where a false positive
    just sends one token to the CPU oracle re-verify (same contract,
    ~23× less elementwise work per window than the full-base compare);
    the final acceptance check keeps the exact ``congruent_zero``.
    """
    mch = c.dA["m"][:nch, None]
    ifch = c.dA["inv_f"][:nch, None]
    xa = _mod_fix(x[0][:nch], mch, ifch)
    ok = jnp.zeros(xa.shape[1], bool)
    for cc in range(max_c):
        ok = ok | jnp.all(xa == c.cp_A[cc][:nch, None], axis=0)
    return ok


def req(c: ECRNSContext, x, y, slack: int):
    """[N] bool: value(x) ≡ value(y) (mod p); x < slack·p bound."""
    d = rsub(c, x, y, slack)
    return congruent_zero(c, d, 2 * slack)


# ---------------------------------------------------------------------------
# Mixed addition (Jacobian accumulator + affine table point), RNS form
# ---------------------------------------------------------------------------

def _madd_rns(c: ECRNSContext, X1, Y1, Z1, inf1, x2, y2):
    """(X1:Y1:Z1) + (x2, y2) with explicit infinity lane.

    State in/out is digit-canonical (< m) with values < 15p (X, Y) /
    11p (Z); x2, y2 < p (tables). Between multiplies the adds/subs are
    LAZY — digit bounds (in units of m) are tracked alongside value
    bounds (units of p) below; every rmul product stays < 32·m² < 2^31
    and outputs digit-canonical; the three results are re-fixed.
    Degenerate same-x cases flagged (CPU oracle re-verifies), matching
    the limb engine's contract.
    """
    # Independent multiplies within a dependency layer share one REDC.
    z1z1 = rmul(c, Z1, Z1)                       # < 3p, digits ≤ m
    u2, z1_3 = rmul_many(c, [(x2, z1z1), (Z1, z1z1)])        # < 3p, ≤ m
    h = rsub(c, u2, X1, 16, guard=1)             # < 19p, ≤ 3m
    zh = radd(c, Z1, h)                          # < 30p, ≤ 4m
    s2, hh, zh2 = rmul_many(
        c, [(y2, z1_3), (h, h), (zh, zh)])       # 9m², 16m² ✓ → ≤ m
    i4 = radd(c, radd(c, hh, hh), radd(c, hh, hh))   # < 12p, ≤ 4m
    s2y1 = rsub(c, s2, Y1, 16, guard=1)          # < 19p, ≤ 3m
    rr = rfix(c, radd(c, s2y1, s2y1))            # < 38p, ≤ m (fixed)
    j, v, r2_ = rmul_many(
        c, [(h, i4), (X1, i4), (rr, rr)])        # 12m², 4m², m² ✓ → ≤ m
    vv = radd(c, v, v)                           # < 6p, ≤ 2m
    X3 = rfix(c, rsub(c, rsub(c, r2_, j, 4, guard=1), vv, 8,
                      guard=2))                  # < 15p, ≤ m (fixed)
    y1j, t5 = rmul_many(
        c, [(Y1, j), (rr, rsub(c, v, X3, 16, guard=1))])   # 3m² ✓ → ≤ m
    Y3 = rfix(c, rsub(c, t5, radd(c, y1j, y1j), 8,
                      guard=2))                  # < 11p, ≤ m (fixed)
    Z3 = rfix(c, rsub(c, rsub(c, zh2, z1z1, 4, guard=1), hh, 4,
                      guard=1))                  # < 11p, ≤ m (fixed)

    deg = ~inf1 & congruent_zero_probe(c, h, 20)  # same-x (incl. inverse)
    return X3, Y3, Z3, deg


def _jadd_rns(c: ECRNSContext, X1, Y1, Z1, inf1, X2, Y2, Z2, inf2):
    """Full Jacobian + Jacobian addition (2007-bl), RNS form.

    Runs ONCE per verify batch (merging the two ladder accumulators),
    so bounds are kept simple with eager rfixes. Inputs are
    digit-canonical with values < 15p (X), < 11p (Y, Z) — the ladder's
    invariants. Outputs match those invariants. Same-x pairs (P = ±Q)
    are flagged degenerate for the CPU oracle, like _madd_rns.
    """
    z1z1, z2z2, z1z2 = rmul_many(
        c, [(Z1, Z1), (Z2, Z2), (Z1, Z2)])           # < 3p, ≤ m
    u1, u2, z1c, z2c = rmul_many(
        c, [(X1, z2z2), (X2, z1z1), (Z1, z1z1), (Z2, z2z2)])  # < 3p, ≤ m
    s1, s2 = rmul_many(c, [(Y1, z2c), (Y2, z1c)])    # < 3p, ≤ m
    h = rsub(c, u2, u1, 4, guard=1)                  # < 7p, ≤ 3m
    t = rsub(c, s2, s1, 4, guard=1)                  # < 7p, ≤ 3m
    rr = rfix(c, radd(c, t, t))                      # < 14p, ≤ m
    hh, r2_ = rmul_many(c, [(h, h), (rr, rr)])       # 9m², 196λ ✓ → ≤ m
    i4 = radd(c, radd(c, hh, hh), radd(c, hh, hh))   # < 12p, ≤ 4m
    zz2 = radd(c, z1z2, z1z2)                        # < 6p, ≤ 2m
    j, v, z3 = rmul_many(
        c, [(h, i4), (u1, i4), (zz2, h)])            # 12m², 84λ ✓ → ≤ m
    v2 = radd(c, v, v)                               # < 6p, ≤ 2m
    X3 = rfix(c, rsub(c, rsub(c, r2_, j, 4, guard=1), v2, 8,
                      guard=2))                      # < 15p, ≤ m
    vx = rsub(c, v, X3, 16, guard=1)                 # < 19p, ≤ 3m
    t5, s1j = rmul_many(c, [(rr, vx), (s1, j)])      # 266λ ✓ → ≤ m
    sj2 = radd(c, s1j, s1j)                          # < 6p, ≤ 2m
    Y3 = rfix(c, rsub(c, t5, sj2, 8, guard=2))       # < 11p, ≤ m
    Z3 = z3                                          # < 3p, ≤ m

    both = ~inf1 & ~inf2
    deg = both & congruent_zero_probe(c, h, 8)       # same x (P = ±Q)
    # infinity lanes: inf1 → P2, inf2 → P1
    X3 = rsel(inf1, X2, rsel(inf2, X1, X3))
    Y3 = rsel(inf1, Y2, rsel(inf2, Y1, Y3))
    Z3 = rsel(inf1, Z2, rsel(inf2, Z1, Z3))
    return X3, Y3, Z3, inf1 & inf2, deg


# the A-domain representation of 1 (= A mod p) as residue columns
def _one_dom(c: ECRNSContext):
    a_mod_p = c.A.prod % c.cp.p
    return (jnp.asarray([a_mod_p % int(m) for m in c.A.m], I32)[:, None],
            jnp.asarray([a_mod_p % int(m) for m in c.B.m], I32)[:, None])


# ---------------------------------------------------------------------------
# Affine window addition: batched RNS inversion + 2M+1S law
# ---------------------------------------------------------------------------

_PM2_BITS: Dict[str, np.ndarray] = {}


def _pm2_bits_np(crv: str) -> np.ndarray:
    """MSB-first bits of p−2 — the field-side Fermat exponent that
    inverts the product tree's root."""
    if crv not in _PM2_BITS:
        p = curve(crv).p
        e = p - 2
        nb = p.bit_length()
        _PM2_BITS[crv] = np.asarray(
            [(e >> (nb - 1 - i)) & 1 for i in range(nb)], np.int32)
    return _PM2_BITS[crv]


def rns_batch_inverse(c: ECRNSContext, den, min_width: int = 128):
    """Simultaneous inversion of an A-domain residue batch mod p.

    den: (A, B) residue pair [I, M], digits ≤ 3m (lazily-grown ok),
    values < 8p, every lane ≢ 0 (mod p), M a power of two. This is
    Montgomery's product-tree trick in rmul form — the same shape as
    ``bignum.batch_mont_inverse`` (rmul is closed over the A-domain:
    rmul(ã, b̃) = (ab)·A, so the tree, the root Fermat p−2 ladder, and
    the walk back down all stay in-domain): ~3 rmuls per lane plus the
    root ladder amortized over min_width lanes, instead of a
    ~1.5·pbits-rmul Fermat per lane. Returns per-lane inverses
    (ĩnv = den⁻¹·A), digit-canonical, values < 3p.
    """
    levels = [den]
    cur = den
    while cur[0].shape[1] > min_width and cur[0].shape[1] % 2 == 0:
        cur = rmul(c, (cur[0][:, 0::2], cur[1][:, 0::2]),
                   (cur[0][:, 1::2], cur[1][:, 1::2]))
        levels.append(cur)

    root = cur
    w = root[0].shape[1]
    bits = jnp.asarray(_pm2_bits_np(c.cp.name))
    one_d = _one_dom(c)
    acc0 = (jnp.broadcast_to(one_d[0], (c.A.count, w)),
            jnp.broadcast_to(one_d[1], (c.B.count, w)))

    def body(i, acc):
        acc = rmul(c, acc, acc)
        mul = rmul(c, acc, root)
        take = jnp.broadcast_to(bits[i] != 0, (w,))
        return rsel(take, mul, acc)

    inv = lax.fori_loop(0, int(bits.shape[0]), body, acc0)

    for lvl in levels[-2::-1]:
        left = (lvl[0][:, 0::2], lvl[1][:, 0::2])
        right = (lvl[0][:, 1::2], lvl[1][:, 1::2])
        il, ir = rmul_many(c, [(inv, right), (inv, left)])
        inv = (jnp.stack([il[0], ir[0]], axis=2).reshape(lvl[0].shape),
               jnp.stack([il[1], ir[1]], axis=2).reshape(lvl[1].shape))
    return inv


def _affine_madd_rns(c: ECRNSContext, x, y, inf, x2, y2, has, one_b):
    """Affine + affine window addition with explicit infinity lane.

    State x, y digit-canonical, values < 3p (stationary); x2, y2 < p
    (table points, never infinity); has: lanes adding this step. The
    division λ = (y2−y)/(x2−x) amortizes into ONE product-tree
    inversion across all lanes (``rns_batch_inverse``); the law itself
    is 3 rmuls (λ = dy·inv, λ², λ·(x−x3)) plus 2 bound-reduction
    rmuls that re-enter the additive results into the < 3p invariant
    (the Jacobian forms get this reduction for free because their
    state only ever passes through multiplies — an affine state is
    used additively, so it must be re-reduced explicitly; this is
    half of where the "2M+1S" headline goes, see docs/PERF.md).

    Exceptional cases, explicit where the complete-ish Jacobian madd
    absorbed them:
    - infinity accumulator + digit > 0 → masked lift of the addend;
    - doubling (P == Q) and inverse (P == −Q → infinity): both have
      x(P) ≡ x2, caught by the 2-channel congruence probe → flagged
      ``degenerate`` (CPU oracle re-verifies — the _madd_rns
      contract), denominator masked to 1 so the tree stays
      invertible.
    """
    dxl = rsub(c, x2, x, 4, guard=1)             # < 5p, ≤ 3m
    dd = has & ~inf & congruent_zero_probe(c, dxl, 5)
    good = has & ~inf & ~dd
    den = rsel(good, dxl, one_b)
    inv = rns_batch_inverse(c, den)              # < 3p, ≤ m
    dyl = rsub(c, y2, y, 4, guard=1)             # < 5p, ≤ 3m
    lam = rmul(c, dyl, inv)                      # 15·λ ✓ → < 3p, ≤ m
    sq = rmul(c, lam, lam)                       # < 3p, ≤ m
    x3l = rsub(c, rsub(c, sq, x, 4, guard=1), x2, 2,
               guard=1)                          # < 9p, ≤ 5m
    xdiff = rsub(c, x, x3l, 16, guard=5)         # < 19p, ≤ 7m
    y3t, x3 = rmul_many(c, [(xdiff, lam), (x3l, one_b)])  # < 3p, ≤ m
    y3l = rsub(c, y3t, y, 4, guard=1)            # < 7p, ≤ 3m
    y3 = rmul(c, y3l, one_b)                     # < 3p, ≤ m
    lift = inf & has
    x3 = rsel(lift, x2, x3)
    y3 = rsel(lift, y2, y3)
    x = rsel(has, x3, x)
    y = rsel(has, y3, y)
    return x, y, inf & ~has, dd


# ---------------------------------------------------------------------------
# The batched verify core
# ---------------------------------------------------------------------------

def _digits_of(u, w_bits: int, n_windows: int):
    """[K, N] u32 16-bit limbs → [n_windows, N] i32 w-bit digits.

    Digits may straddle limb boundaries for w ∤ 16 (the 12-bit path);
    an appended zero limb covers the top window's spill.
    """
    up = jnp.concatenate(
        [u, jnp.zeros((1, u.shape[1]), u.dtype)], axis=0)
    mask = (1 << w_bits) - 1
    outs = []
    for j in range(n_windows):
        b = w_bits * j
        l, o = b >> 4, b & 15
        d = up[l] >> o
        if o + w_bits > 16:
            d = d | (up[l + 1] << (16 - o))
        outs.append(d & mask)
    return jnp.stack(outs).astype(jnp.int32)


@partial(jax.jit, static_argnames=("crv", "nbits", "wbits", "ladder"))
def _ecdsa_rns_core(r, s, e, key_idx, tab,
                    n, npp, nr2, none_, nm2,
                    crv: str, nbits: int, wbits: int = 8,
                    ladder: str = "jacobian"):
    """ECDSA verify: scalar math in limbs, point math in RNS.

    r, s, e: [K, N] limb values; key_idx [N]; ``tab``: THE fused
    window-major packed window table (ECRNSKeyTable.tab —
    [W·(nk+1)·per, 2·iap] i32 A|B<<16 words, G at slot 0).
    n..nm2: [K, 1] scalar-field constants. ``ladder`` selects the
    window-add law — ``jacobian`` (mixed madd, default) or ``affine``
    (2M+1S adds + one batched product-tree inversion per window step,
    ec.ladder_mode). Returns (ok, deg) [N] bools.
    """
    from . import bignum as B

    c = ctx_for(crv, wbits)
    k = r.shape[0]
    shape = r.shape
    nb = jnp.broadcast_to(n, shape)
    nppb = jnp.broadcast_to(npp, shape)
    nr2b = jnp.broadcast_to(nr2, shape)

    # 1. range checks + s⁻¹ (limb domain, batch inverse tree)
    r_ok = ~B.is_zero(r) & ~B.compare_ge(r, nb)
    s_ok = ~B.is_zero(s) & ~B.compare_ge(s, nb)
    one_plain = jnp.zeros_like(r).at[0].set(1)
    s_safe = jnp.where(s_ok[None, :], s, one_plain)
    s_m = B.mont_mul(s_safe, nr2b, nb, nppb)
    w_m = B.batch_mont_inverse(s_m, n, npp, nr2, none_, nm2, nbits=nbits)
    u1 = B.mont_mul(e, w_m, nb, nppb)
    u2 = B.mont_mul(r, w_m, nb, nppb)

    # 2. window digits (w-bit, limb-boundary-straddling for w ∤ 16)
    n_windows = c.n_windows
    per = (1 << wbits) - 1

    dig1 = _digits_of(u1, wbits, n_windows)
    dig2 = _digits_of(u2, wbits, n_windows)

    ia = c.A.count
    ib = c.B.count
    iap = packed_cols(c)

    # 3. TWO-ACCUMULATOR ladder: the per-window G-digit and Q-digit
    # additions are independent chains, so both run as ONE mixed-add
    # over a [I, 2N] concatenated state — the same 5 REDC layers per
    # window serve both chains (half the dependency depth of
    # interleaving them). The x and y window tables fuse into one
    # [rows, 2I] table so each step costs ONE gather (same bytes, half
    # the gather dispatches). A 4-chain even/odd split (16 steps at 4N
    # lanes) measured SLOWER on the chip — per-layer cost here scales
    # with lane width (bandwidth-bound), so halving depth while
    # doubling width nets negative with the extra merge adds
    # (docs/PERF.md A/B). The accumulators merge with one Jacobian add.
    n_tok = shape[1]
    zA = jnp.zeros((c.A.count, 2 * n_tok), I32)
    zB = jnp.zeros((c.B.count, 2 * n_tok), I32)
    X = (zA, zB)
    Y = (zA, zB)
    Z = (zA, zB)
    inf = jnp.ones(2 * n_tok, bool)
    deg0 = jnp.zeros(2 * n_tok, bool)
    one_d = _one_dom(c)

    # tab is window-major ([window][slot][digit], G at slot 0 —
    # ECRNSKeyTable): a window's gather touches ONE contiguous
    # (nk+1)·per-row block.
    nk = tab.shape[0] // (n_windows * per) - 1
    win_stride = (nk + 1) * per
    key_base = (key_idx.astype(jnp.int32) + 1) * per

    def gather_pt(idx):
        # Packed i32 rows (A|B<<16 per word): half the gather bytes of
        # the old [rows, 2I] layout at native word granularity.
        g = jnp.take(tab, idx, axis=0).T          # [2·iap, M] packed
        return g[:iap], g[iap:]

    from . import pallas_madd

    use_fused = pallas_madd.enabled()
    interp = jax.default_backend() == "cpu"   # interpret mode on CPU

    def add_from_table(state, d, row0):
        X, Y, Z, inf, deg = state
        has = d > 0
        idx = row0 + jnp.where(has, d - 1, 0)
        x2p, y2p = gather_pt(idx)
        if use_fused:
            # One VMEM-resident kernel for the whole mixed-add incl.
            # the lift/select bookkeeping and the table-word unpack
            # (pallas_madd).
            Xn, Yn, Zn, dd = pallas_madd.madd_fused(
                c, X, Y, Z, inf, has, x2p, y2p, interpret=interp)
            return Xn, Yn, Zn, inf & ~has, deg | dd
        x2 = unpack_pt(x2p, ia, ib)
        y2 = unpack_pt(y2p, ia, ib)
        X3, Y3, Z3, dd = _madd_rns(c, X, Y, Z, inf, x2, y2)
        # infinity accumulator: result is the (lifted) affine addend
        lift = inf & has
        X3 = rsel(lift, x2, X3)
        Y3 = rsel(lift, y2, Y3)
        Z3 = rsel(lift,
                  (jnp.broadcast_to(one_d[0], Z3[0].shape),
                   jnp.broadcast_to(one_d[1], Z3[1].shape)), Z3)
        sel = has
        X = rsel(sel, X3, X)
        Y = rsel(sel, Y3, Y)
        Z = rsel(sel, Z3, Z)
        deg = deg | (dd & has & ~lift)
        inf = inf & ~has
        return X, Y, Z, inf, deg

    def ladder_body(i, state):
        d1 = lax.dynamic_slice_in_dim(dig1, i, 1, axis=0)[0]
        d2 = lax.dynamic_slice_in_dim(dig2, i, 1, axis=0)[0]
        d = jnp.concatenate([d1, d2])
        row0 = jnp.concatenate(
            [jnp.full((n_tok,), i * win_stride, jnp.int32),
             i * win_stride + key_base])
        return add_from_table(state, d, row0)

    if ladder == "affine":
        # Affine-law ladder (the round-5 verdict A/B): same two-chain
        # lane concat, same digits and table rows, but the accumulator
        # stays affine and each window's divisions amortize into ONE
        # batched product-tree inversion over the 2N lanes. The merge
        # and final projective check below are shared — the affine
        # chains lift to Jacobian with Z = 1.
        one_bc = (jnp.broadcast_to(one_d[0], (ia, 2 * n_tok)),
                  jnp.broadcast_to(one_d[1], (ib, 2 * n_tok)))

        def affine_body(i, state):
            xv, yv, infv, degv = state
            d1 = lax.dynamic_slice_in_dim(dig1, i, 1, axis=0)[0]
            d2 = lax.dynamic_slice_in_dim(dig2, i, 1, axis=0)[0]
            d = jnp.concatenate([d1, d2])
            row0 = jnp.concatenate(
                [jnp.full((n_tok,), i * win_stride, jnp.int32),
                 i * win_stride + key_base])
            has = d > 0
            idx = row0 + jnp.where(has, d - 1, 0)
            x2p, y2p = gather_pt(idx)
            x2 = unpack_pt(x2p, ia, ib)
            y2 = unpack_pt(y2p, ia, ib)
            xv, yv, infv, dd = _affine_madd_rns(
                c, xv, yv, infv, x2, y2, has, one_d)
            return xv, yv, infv, degv | dd

        X2, Y2, inf2, deg2 = lax.fori_loop(
            0, n_windows, affine_body, (one_bc, one_bc, inf, deg0))
        Z2 = one_bc
    elif use_fused and pallas_madd.ladder_enabled():
        # Whole-ladder fusion: one pallas_call, state VMEM-resident
        # across all windows (pallas_madd.ladder_fused). Same math,
        # same table rows, same masks — the per-window path above
        # remains the A/B reference.
        w_ids = jnp.arange(n_windows, dtype=jnp.int32)[:, None]
        d_all = jnp.concatenate([dig1, dig2], axis=1)
        row0_all = jnp.concatenate(
            [jnp.broadcast_to(w_ids * win_stride, (n_windows, n_tok)),
             key_base[None, :] + w_ids * win_stride], axis=1)
        X2, Y2, Z2, inf2, deg2 = pallas_madd.ladder_fused(
            c, tab, d_all, row0_all, interpret=interp)
    else:
        X2, Y2, Z2, inf2, deg2 = lax.fori_loop(
            0, n_windows, ladder_body, (X, Y, Z, inf, deg0))

    def half(pair, lo):
        return (lax.dynamic_slice_in_dim(pair[0], lo, n_tok, axis=1),
                lax.dynamic_slice_in_dim(pair[1], lo, n_tok, axis=1))

    Xg, Yg, Zg = half(X2, 0), half(Y2, 0), half(Z2, 0)
    Xq, Yq, Zq = (half(X2, n_tok), half(Y2, n_tok), half(Z2, n_tok))
    inf_g, inf_q = inf2[:n_tok], inf2[n_tok:]
    deg = deg2[:n_tok] | deg2[n_tok:]

    X, Y, Z, inf, deg_j = _jadd_rns(c, Xg, Yg, Zg, inf_g,
                                    Xq, Yq, Zq, inf_q)
    deg = deg | deg_j

    # 4. projective check in RNS: X ≡ r·Z² (or (r+n)·Z² when r+n < p)
    rA = _limb_pair_to_rns(c, r)
    r_dom = rmul(c, rA, c.A2)                    # r·A, < 3p
    z2 = rmul(c, Z, Z)
    rhs1 = rmul(c, r_dom, z2)
    ok1 = req(c, X, rhs1, 16)

    zero_row = jnp.zeros_like(r[:1])
    rpn = B.carry_normalize(jnp.concatenate([r + nb, zero_row], axis=0))
    p_limbs = jnp.asarray(c.cp.p_limbs, jnp.uint32)[:, None]
    p_pad = jnp.concatenate(
        [jnp.broadcast_to(p_limbs, shape), zero_row], axis=0)
    rpn_lt_p = ~B.compare_ge(rpn, p_pad)
    rpnA = _limb_pair_to_rns(c, rpn[:k])
    rpn_dom = rmul(c, rpnA, c.A2)
    rhs2 = rmul(c, rpn_dom, z2)
    ok2 = req(c, X, rhs2, 16) & rpn_lt_p

    ok = r_ok & s_ok & ~inf & (ok1 | ok2)
    return ok, deg & r_ok & s_ok


def _limb_pair_to_rns(c: ECRNSContext, limbs):
    """[K, N] u32 limbs → plain residue pair via the conversion mats."""
    from .rns import _limbs_to_rns

    return (_limbs_to_rns(limbs, c.T_A, c.dA),
            _limbs_to_rns(limbs, c.T_B, c.dB))


# ---------------------------------------------------------------------------
# Key tables in RNS form
# ---------------------------------------------------------------------------

def packed_cols(c) -> int:
    """Packed-word columns per coordinate: max(I_A, I_B)."""
    return max(c.A.count, c.B.count)


def _pack_residue_rows(c, r: np.ndarray) -> np.ndarray:
    """[rows, I_A + I_B] residues → [rows, max(I_A, I_B)] i32 words.

    Word j holds A-channel j in its low 16 bits and B-channel j in its
    high 16 (residues < 2^13). TPU gathers are word-granular — an i16
    table measured 2.4× SLOWER to gather than i32 — so packing pairs
    halves the gather bytes while keeping native i32 rows; the kernels
    unpack with one mask and one shift on VMEM tiles.
    """
    ia, ib = c.A.count, c.B.count
    out = np.zeros((r.shape[0], packed_cols(c)), np.int32)
    out[:, :ia] = r[:, :ia]
    out[:, :ib] |= r[:, ia:].astype(np.int32) << 16
    return out


def unpack_pt(g, ia: int, ib: int):
    """[iap, M] packed words → ((A [ia, M], B [ib, M])) i32 planes.

    THE unpack for _pack_residue_rows' format — also called inside
    the Pallas kernels (pallas_madd), so a packing change has exactly
    one encode and one decode to keep in sync.
    """
    return ((g & 0xFFFF)[:ia], (g >> 16)[:ib])


class ECRNSKeyTable:
    """THE device window table for one curve + key set.

    ``tab``: [n_windows·(nk+1)·per, 2·iap] i32, window-major with G as
    slot 0 and key k as slot k+1; each row is the packed x residues
    (iap words, A|B<<16 — _pack_residue_rows) followed by the packed
    y residues. Window-major means a window's gather touches ONE
    contiguous (nk+1)·per-row block; fusing x‖y means one take per
    window. Built ONCE here (host numpy), so no per-dispatch
    reordering ever runs on device. Row addressing (see
    _ecdsa_rns_core): window i, slot s, digit d → row
    i·(nk+1)·per + s·per + (d−1).
    """

    def __init__(self, crv: str, keys: Sequence,
                 w_bits: Optional[int] = None):
        self.ctx = ctx_for(crv, w_bits)
        self.cp = self.ctx.cp
        c = self.ctx
        self.nk = nk = len(keys)
        per = (1 << c.w_bits) - 1
        nw = c.n_windows
        iap = packed_cols(c)
        gx, gy = _g_packed_np(crv, c.w_bits)
        parts = [(gx, gy)]
        for key in keys:
            nums = key.public_numbers()
            rx, ry = _window_residue_rows(c, (nums.x, nums.y))
            parts.append((_pack_residue_rows(c, rx),
                          _pack_residue_rows(c, ry)))
        # [slots, W, per, iap] → window-major [W, slots, per, iap]
        tx = np.stack([px.reshape(nw, per, iap) for px, _ in parts])
        ty = np.stack([py.reshape(nw, per, iap) for _, py in parts])
        tx = tx.transpose(1, 0, 2, 3).reshape(nw * (nk + 1) * per, iap)
        ty = ty.transpose(1, 0, 2, 3).reshape(nw * (nk + 1) * per, iap)
        self.tab = jnp.asarray(np.concatenate([tx, ty], axis=1))


def _residue_matrix(c: ECRNSContext, vals: List[int]) -> np.ndarray:
    """[len(vals), I_A + I_B] i32 residues of host ints < p, vectorized.

    Bytes-of-value × (256^j mod mᵢ) as one f64 BLAS matmul — exact,
    since every term is < 255·2^13 and ≤ 67 terms sum < 2^53 — then a
    single i64 %. Replaces the per-row residues_of() python loop (the
    12-bit tables have 90k rows/key; per-row conversion was seconds).
    """
    cp = c.cp
    nb = (cp.p.bit_length() + 7) // 8 + 1
    blob = b"".join(v.to_bytes(nb, "little") for v in vals)
    mat = np.frombuffer(blob, np.uint8).reshape(len(vals), nb)
    ms = np.concatenate([np.asarray(c.A.m, np.int64),
                         np.asarray(c.B.m, np.int64)])
    powm = np.empty((nb, len(ms)), np.int64)
    for i, m in enumerate(ms):
        mi = int(m)
        powm[:, i] = [pow(256, j, mi) for j in range(nb)]
    acc = mat.astype(np.float64) @ powm.astype(np.float64)
    return (acc.astype(np.int64) % ms[None, :]).astype(np.int32)


def _window_residue_rows(c: ECRNSContext, point) -> Tuple[np.ndarray,
                                                          np.ndarray]:
    """Host: w-bit window table of d·2^{w·i}·point as A-domain residues.

    Row i·(2^w−1) + (d−1) holds d·2^{w·i}·point. The affine multiples
    come from the Jacobian chain + one batched inversion
    (CurveParams.window_multiples), residues from the vectorized
    converter — together ~0.5 s/key for the 12-bit P-256 tables.
    """
    cp = c.cp
    p = cp.p
    a_mod = c.A.prod % p
    X, Y = cp.window_multiples(point, c.w_bits, c.n_windows)
    rx = _residue_matrix(c, [x * a_mod % p for x in X])
    ry = _residue_matrix(c, [y * a_mod % p for y in Y])
    return rx, ry


_G_PACKED_NP: Dict[tuple, tuple] = {}


def _g_packed_np(crv: str, w_bits: int):
    """Host-cached packed G window rows (x, y), each [W·per, iap]."""
    key = (crv, w_bits)
    if key not in _G_PACKED_NP:
        c = ctx_for(crv, w_bits)
        rx, ry = _window_residue_rows(c, (c.cp.gx, c.cp.gy))
        _G_PACKED_NP[key] = (_pack_residue_rows(c, rx),
                             _pack_residue_rows(c, ry))
    return _G_PACKED_NP[key]
