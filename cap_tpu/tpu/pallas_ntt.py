"""Fused layered-butterfly NTT/INTT over Z_8380417 as Pallas kernels.

The stagewise jnp graph in ``ntt.py`` materializes the whole
``[..., 256]`` lane array to HBM between each of the 8 butterfly
stages — the same per-layer traffic tax the RNS REDC layers paid
before ``pallas_madd`` (docs/PERF.md round-3: measured ~6x the pure
read+write traffic per layer). This module runs ALL 8 stages (forward
or inverse, including the folded 256⁻¹ scaling) on one VMEM tile per
row block: HBM is touched once for inputs and once for outputs, the
shape of the win the GPU Dilithium engine (PAPERS.md, arxiv
2211.12265) demonstrates for exactly this transform.

Arithmetic is ``ntt.py``'s verbatim: uint32 Montgomery lanes, 16-bit
limb ``_mulhi32`` REDC, no int64 anywhere (``mont_mul``/``add_q``/
``sub_q`` are imported and used unchanged, so the two paths cannot
drift). Twiddles ride in Montgomery form as kernel constants.

Numerical contract: bit-identical to ``ntt.ntt``/``ntt.intt`` and the
int64 ``ntt_ref``/``intt_ref`` host references — pinned by
tests/test_pallas_ntt.py in interpret mode on CPU and by
``make pallas-smoke``. Enabled via CAP_TPU_PALLAS_NTT (default ON for
TPU backends; CPU keeps the XLA path — interpret mode is a
correctness harness, and the bench_stages kernel rows publish the
honest CPU A/B).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional

import numpy as np

from . import ntt as _ntt

_TILE_R = int(os.environ.get("CAP_TPU_NTT_TILE", 256))    # rows/step
N = _ntt.N


def enabled() -> bool:
    """Fused Pallas NTT: CAP_TPU_PALLAS_NTT=1/0 overrides; default ON
    only for accelerator backends (the pallas_madd stance)."""
    v = os.environ.get("CAP_TPU_PALLAS_NTT")
    if v is not None:
        return v not in ("0", "false", "no")
    import jax

    return jax.default_backend() == "tpu"


def _ntt_stages(x, zetas):
    """All 8 forward Cooley-Tukey stages on a [R, 256] VALUE (VMEM
    array in-kernel). Butterfly-for-butterfly ntt.ntt's loop body."""
    import jax.numpy as jnp

    r = x.shape[0]
    for s in range(8):
        ln = 128 >> s
        nblk = N // (2 * ln)
        z = zetas[nblk: 2 * nblk]                    # [nblk]
        v = x.reshape(r, nblk, 2, ln)
        lo_, hi_ = v[:, :, 0, :], v[:, :, 1, :]
        t = _ntt.mont_mul(z[None, :, None], hi_)
        x = jnp.stack([_ntt.add_q(lo_, t), _ntt.sub_q(lo_, t)],
                      axis=2).reshape(r, N)
    return x


def _intt_stages(x, neg_zetas, inv256):
    """All 8 Gentleman-Sande inverse stages + the folded 256⁻¹ scale
    on a [R, 256] value; ntt.intt's loop body verbatim."""
    import jax.numpy as jnp

    r = x.shape[0]
    for s in range(8):
        ln = 1 << s
        nblk = N // (2 * ln)
        z = neg_zetas[nblk: 2 * nblk][::-1]
        v = x.reshape(r, nblk, 2, ln)
        lo_, hi_ = v[:, :, 0, :], v[:, :, 1, :]
        t = lo_
        lo_ = _ntt.add_q(t, hi_)
        hi_ = _ntt.mont_mul(z[None, :, None], _ntt.sub_q(t, hi_))
        x = jnp.stack([lo_, hi_], axis=2).reshape(r, N)
    return _ntt.mont_mul(inv256[0, 0], x)


def _ntt_kernel(x_ref, z_ref, o_ref):
    o_ref[:] = _ntt_stages(x_ref[:], z_ref[:][0])


def _intt_kernel(x_ref, z_ref, inv_ref, o_ref):
    o_ref[:] = _intt_stages(x_ref[:], z_ref[:][0], inv_ref[:])


def _call(x2, inverse: bool, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    @partial(jax.jit, static_argnames=("inverse", "interpret"))
    def run(x2, zetas, inv, inverse: bool, interpret: bool):
        rows = x2.shape[0]
        grid = rows // _TILE_R
        spec = pl.BlockSpec((_TILE_R, N), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
        z_spec = pl.BlockSpec((1, N), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
        inv_spec = pl.BlockSpec((1, 1), lambda i: (0, 0),
                                memory_space=pltpu.VMEM)
        out = jax.ShapeDtypeStruct((rows, N), jnp.uint32)
        if inverse:
            return pl.pallas_call(
                _intt_kernel, out_shape=out, grid=(grid,),
                in_specs=[spec, z_spec, inv_spec], out_specs=spec,
                interpret=interpret)(x2, zetas, inv)
        return pl.pallas_call(
            _ntt_kernel, out_shape=out, grid=(grid,),
            in_specs=[spec, z_spec], out_specs=spec,
            interpret=interpret)(x2, zetas)

    zetas = jnp.asarray((_ntt.NEG_ZETAS_MONT if inverse
                         else _ntt.ZETAS_MONT)[None, :])
    inv = jnp.asarray(np.array([[_ntt.INV256_MONT]], np.uint32))
    return run(x2, zetas, inv, inverse, interpret)


def _apply(x, inverse: bool, interpret: Optional[bool]):
    import jax.numpy as jnp

    if interpret is None:
        import jax

        interpret = jax.default_backend() != "tpu"
    shape = x.shape
    rows = 1
    for s in shape[:-1]:
        rows *= s
    x2 = x.reshape(rows, N)
    pad = (-rows) % _TILE_R
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = _call(x2, inverse, interpret)
    if pad:
        out = out[:rows]
    return out.reshape(shape)


def ntt_fused(x, interpret: Optional[bool] = None):
    """Forward NTT on ``[..., 256]`` uint32 lanes in [0, q) — one
    kernel, bit-identical to ``ntt.ntt``."""
    return _apply(x, False, interpret)


def intt_fused(x, interpret: Optional[bool] = None):
    """Inverse NTT (scaling folded) — one kernel, bit-identical to
    ``ntt.intt``."""
    return _apply(x, True, interpret)
