"""Batched SLH-DSA (FIPS 205, SPHINCS+) signature verification.

The second post-quantum verify family — and the proof that the
batched device Keccak plane (``pallas_keccak``) is the whole game:
SLH-DSA is *pure hash*. One verify walks ~2-6k SHAKE256 evaluations
(FORS leaf/auth recomputation, d layers of WOTS+ chains, Merkle auth
paths), every one of them a fixed-shape one-to-five-block absorb —
exactly the lane workload the NIST-PQC FPGA comparison (PAPERS.md,
arxiv 2606.15744) identifies as the fast-verify bottleneck.

Split (the mldsa.py stance):

- **host** (numpy byte shuffling + ONE hashlib SHAKE per token): sig
  length gate and field split, H_msg → md / idx_tree / idx_leaf /
  FORS indices, and — because every tree/leaf index is then known —
  ALL 500ish ADRS words per token precomputed as interleaved lanes;
- **device** (jnp over ``pallas_keccak``): every F/H/T evaluation —
  FORS leaves + auth folds + T_k, then a ``lax.scan`` over the d
  hypertree layers (WOTS digit extraction from the running root,
  masked 15-step chain walk with the dynamic hash-address injected
  into the ADRS lanes on-device, T_len, XMSS auth fold), ending in an
  on-device root compare against the key table. Verdict bits come
  back; nothing else does.

``py_verify`` is the pure hashlib host oracle (independent of the
numpy Keccak reference — two implementations cross-pin each other);
keygen and the deterministic signer exist ONLY for fixtures (KATs,
bench tokens, chaos traffic) and are nowhere near constant-time.

Parameter sets: SLH-DSA-SHAKE-128s and -128f (FIPS 205 Table 2), the
NIST category-1 pair — "s" small-signature/slow, "f" fast. JOSE alg
names follow draft-ietf-cose-sphincs-plus (the names ARE the set
names, the ML-DSA convention).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# ADRS type words (FIPS 205 §4.2)
_WOTS_HASH = 0
_WOTS_PK = 1
_TREE = 2
_FORS_TREE = 3
_FORS_ROOTS = 4
_WOTS_PRF = 5
_FORS_PRF = 6

W = 16                              # Winternitz (lg_w = 4, all sets)
LG_W = 4


class ParameterSet:
    """One FIPS 205 parameter set (Table 2) plus derived sizes."""

    __slots__ = ("name", "n", "h", "d", "hp", "a", "k", "m",
                 "len1", "len2", "wlen", "pk_size", "sig_size")

    def __init__(self, name: str, n: int, h: int, d: int, hp: int,
                 a: int, k: int, m: int):
        self.name = name
        self.n, self.h, self.d, self.hp = n, h, d, hp
        self.a, self.k, self.m = a, k, m
        self.len1 = 2 * n                     # 8n / lg_w
        self.len2 = 3                         # lg_w = 4, n = 16..32
        self.wlen = self.len1 + self.len2
        self.pk_size = 2 * n
        self.sig_size = n * (1 + k * (1 + a) + h + d * self.wlen)


PARAMS: Dict[str, ParameterSet] = {
    "SLH-DSA-SHAKE-128s": ParameterSet("SLH-DSA-SHAKE-128s",
                                       16, 63, 7, 9, 12, 14, 30),
    "SLH-DSA-SHAKE-128f": ParameterSet("SLH-DSA-SHAKE-128f",
                                       16, 66, 22, 3, 6, 33, 34),
}

SLHDSA_ALGS = tuple(PARAMS)         # the JOSE alg names ARE the names


def _shake(data: bytes, outlen: int) -> bytes:
    return hashlib.shake_256(data).digest(outlen)


# ---------------------------------------------------------------------------
# ADRS — 32 bytes, big-endian words (§4.2; SHAKE uses the full form)
# ---------------------------------------------------------------------------

class ADRS:
    __slots__ = ("b",)

    def __init__(self, b: Optional[bytearray] = None):
        self.b = bytearray(32) if b is None else bytearray(b)

    def copy(self) -> "ADRS":
        return ADRS(self.b)

    def set_layer(self, v: int) -> None:
        self.b[0:4] = v.to_bytes(4, "big")

    def set_tree(self, v: int) -> None:
        self.b[4:16] = v.to_bytes(12, "big")

    def set_type_and_clear(self, t: int) -> None:
        self.b[16:20] = t.to_bytes(4, "big")
        self.b[20:32] = bytes(12)

    def set_keypair(self, v: int) -> None:
        self.b[20:24] = v.to_bytes(4, "big")

    def set_chain(self, v: int) -> None:      # == tree height word
        self.b[24:28] = v.to_bytes(4, "big")

    set_tree_height = set_chain

    def set_hash(self, v: int) -> None:       # == tree index word
        self.b[28:32] = v.to_bytes(4, "big")

    set_tree_index = set_hash

    def tree_index(self) -> int:
        return int.from_bytes(self.b[28:32], "big")

    def bytes(self) -> bytes:
        return bytes(self.b)


# ---------------------------------------------------------------------------
# integer / bit codecs (§4.1)
# ---------------------------------------------------------------------------

def base_2b(data: bytes, b: int, out_len: int) -> List[int]:
    """MSB-first b-bit groups from a byte string (Algorithm 4)."""
    vals = []
    acc = 0
    bits = 0
    i = 0
    for _ in range(out_len):
        while bits < b:
            acc = (acc << 8) | data[i]
            i += 1
            bits += 8
        bits -= b
        vals.append((acc >> bits) & ((1 << b) - 1))
    return vals


def _wots_digits(msg: bytes, p: ParameterSet) -> List[int]:
    """len1 message nibbles + the 3 checksum nibbles (Algorithms 7/8's
    shared digit schedule: csum left-shifted 4, big-endian)."""
    digits = base_2b(msg, LG_W, p.len1)
    csum = sum(W - 1 - d for d in digits)
    return digits + [(csum >> 8) & 15, (csum >> 4) & 15, csum & 15]


# ---------------------------------------------------------------------------
# hash primitives (SHAKE instantiation, §11.1)
# ---------------------------------------------------------------------------

def _F(pk_seed: bytes, adrs: ADRS, m: bytes, n: int) -> bytes:
    return _shake(pk_seed + adrs.bytes() + m, n)


_H = _F                              # same construction, 2n message
_T = _F                              # same construction, l*n message


def _PRF(pk_seed: bytes, sk_seed: bytes, adrs: ADRS, n: int) -> bytes:
    return _shake(pk_seed + adrs.bytes() + sk_seed, n)


# ---------------------------------------------------------------------------
# WOTS+ / XMSS / FORS host implementation (sign side is fixture-only)
# ---------------------------------------------------------------------------

def _chain(x: bytes, start: int, steps: int, pk_seed: bytes,
           adrs: ADRS, n: int) -> bytes:
    for j in range(start, start + steps):
        adrs.set_hash(j)
        x = _F(pk_seed, adrs, x, n)
    return x


def _wots_pk_gen(sk_seed: bytes, pk_seed: bytes, adrs: ADRS,
                 p: ParameterSet) -> bytes:
    sk_adrs = adrs.copy()
    sk_adrs.set_type_and_clear(_WOTS_PRF)
    sk_adrs.b[20:24] = adrs.b[20:24]
    tmp = b""
    for i in range(p.wlen):
        sk_adrs.set_chain(i)
        sk = _PRF(pk_seed, sk_seed, sk_adrs, p.n)
        adrs.set_chain(i)
        tmp += _chain(sk, 0, W - 1, pk_seed, adrs, p.n)
    pk_adrs = adrs.copy()
    pk_adrs.set_type_and_clear(_WOTS_PK)
    pk_adrs.b[20:24] = adrs.b[20:24]
    return _T(pk_seed, pk_adrs, tmp, p.n)


def _wots_sign(msg: bytes, sk_seed: bytes, pk_seed: bytes, adrs: ADRS,
               p: ParameterSet) -> bytes:
    digits = _wots_digits(msg, p)
    sk_adrs = adrs.copy()
    sk_adrs.set_type_and_clear(_WOTS_PRF)
    sk_adrs.b[20:24] = adrs.b[20:24]
    sig = b""
    for i, dgt in enumerate(digits):
        sk_adrs.set_chain(i)
        sk = _PRF(pk_seed, sk_seed, sk_adrs, p.n)
        adrs.set_chain(i)
        sig += _chain(sk, 0, dgt, pk_seed, adrs, p.n)
    return sig


def _wots_pk_from_sig(sig: bytes, msg: bytes, pk_seed: bytes,
                      adrs: ADRS, p: ParameterSet) -> bytes:
    digits = _wots_digits(msg, p)
    n = p.n
    tmp = b""
    for i, dgt in enumerate(digits):
        adrs.set_chain(i)
        tmp += _chain(sig[i * n: (i + 1) * n], dgt, W - 1 - dgt,
                      pk_seed, adrs, n)
    pk_adrs = adrs.copy()
    pk_adrs.set_type_and_clear(_WOTS_PK)
    pk_adrs.b[20:24] = adrs.b[20:24]
    return _T(pk_seed, pk_adrs, tmp, n)


def _xmss_node(sk_seed: bytes, i: int, z: int, pk_seed: bytes,
               adrs: ADRS, p: ParameterSet) -> bytes:
    if z == 0:
        adrs.set_type_and_clear(_WOTS_HASH)
        adrs.set_keypair(i)
        return _wots_pk_gen(sk_seed, pk_seed, adrs, p)
    l = _xmss_node(sk_seed, 2 * i, z - 1, pk_seed, adrs, p)
    r = _xmss_node(sk_seed, 2 * i + 1, z - 1, pk_seed, adrs, p)
    adrs.set_type_and_clear(_TREE)
    adrs.set_tree_height(z)
    adrs.set_tree_index(i)
    return _H(pk_seed, adrs, l + r, p.n)


def _xmss_sign(msg: bytes, sk_seed: bytes, idx: int, pk_seed: bytes,
               adrs: ADRS, p: ParameterSet) -> bytes:
    auth = b""
    for j in range(p.hp):
        k = (idx >> j) ^ 1
        auth += _xmss_node(sk_seed, k, j, pk_seed, adrs.copy(), p)
    adrs.set_type_and_clear(_WOTS_HASH)
    adrs.set_keypair(idx)
    return _wots_sign(msg, sk_seed, pk_seed, adrs, p) + auth


def _xmss_pk_from_sig(idx: int, sig_xmss: bytes, msg: bytes,
                      pk_seed: bytes, adrs: ADRS,
                      p: ParameterSet) -> bytes:
    n = p.n
    adrs.set_type_and_clear(_WOTS_HASH)
    adrs.set_keypair(idx)
    sig = sig_xmss[: p.wlen * n]
    auth = sig_xmss[p.wlen * n:]
    node = _wots_pk_from_sig(sig, msg, pk_seed, adrs, p)
    adrs.set_type_and_clear(_TREE)
    adrs.set_tree_index(idx)
    for lev in range(p.hp):
        adrs.set_tree_height(lev + 1)
        a_node = auth[lev * n: (lev + 1) * n]
        if (idx >> lev) & 1 == 0:
            adrs.set_tree_index(adrs.tree_index() // 2)
            node = _H(pk_seed, adrs, node + a_node, n)
        else:
            adrs.set_tree_index((adrs.tree_index() - 1) // 2)
            node = _H(pk_seed, adrs, a_node + node, n)
    return node


def _fors_node(sk_seed: bytes, i: int, z: int, pk_seed: bytes,
               adrs: ADRS, p: ParameterSet) -> bytes:
    if z == 0:
        sk_adrs = adrs.copy()
        sk_adrs.set_type_and_clear(_FORS_PRF)
        sk_adrs.b[20:24] = adrs.b[20:24]
        sk_adrs.set_tree_index(i)
        sk = _PRF(pk_seed, sk_seed, sk_adrs, p.n)
        adrs.set_tree_height(0)
        adrs.set_tree_index(i)
        return _F(pk_seed, adrs, sk, p.n)
    l = _fors_node(sk_seed, 2 * i, z - 1, pk_seed, adrs, p)
    r = _fors_node(sk_seed, 2 * i + 1, z - 1, pk_seed, adrs, p)
    adrs.set_tree_height(z)
    adrs.set_tree_index(i)
    return _H(pk_seed, adrs, l + r, p.n)


def _fors_sign(md: bytes, sk_seed: bytes, pk_seed: bytes, adrs: ADRS,
               p: ParameterSet) -> bytes:
    indices = base_2b(md, p.a, p.k)
    sig = b""
    for i, idx in enumerate(indices):
        sk_adrs = adrs.copy()
        sk_adrs.set_type_and_clear(_FORS_PRF)
        sk_adrs.b[20:24] = adrs.b[20:24]
        sk_adrs.set_tree_index(i * (1 << p.a) + idx)
        sig += _PRF(pk_seed, sk_seed, sk_adrs, p.n)
        for j in range(p.a):
            s = (idx >> j) ^ 1
            sig += _fors_node(sk_seed, i * (1 << (p.a - j)) + s, j,
                              pk_seed, adrs.copy(), p)
    return sig


def _fors_pk_from_sig(sig_fors: bytes, md: bytes, pk_seed: bytes,
                      adrs: ADRS, p: ParameterSet) -> bytes:
    n = p.n
    indices = base_2b(md, p.a, p.k)
    roots = b""
    for i, idx in enumerate(indices):
        off = i * (1 + p.a) * n
        sk = sig_fors[off: off + n]
        adrs.set_tree_height(0)
        adrs.set_tree_index(i * (1 << p.a) + idx)
        node = _F(pk_seed, adrs, sk, n)
        auth = sig_fors[off + n: off + (1 + p.a) * n]
        for j in range(p.a):
            a_node = auth[j * n: (j + 1) * n]
            adrs.set_tree_height(j + 1)
            if (idx >> j) & 1 == 0:
                adrs.set_tree_index(adrs.tree_index() // 2)
                node = _H(pk_seed, adrs, node + a_node, n)
            else:
                adrs.set_tree_index((adrs.tree_index() - 1) // 2)
                node = _H(pk_seed, adrs, a_node + node, n)
        roots += node
    pk_adrs = adrs.copy()
    pk_adrs.set_type_and_clear(_FORS_ROOTS)
    pk_adrs.b[20:24] = adrs.b[20:24]
    return _T(pk_seed, pk_adrs, roots, n)


# ---------------------------------------------------------------------------
# message digest split (§9.3 / §10.2)
# ---------------------------------------------------------------------------

def _digest_split(digest: bytes,
                  p: ParameterSet) -> Tuple[bytes, int, int]:
    ka8 = (p.k * p.a + 7) // 8
    t8 = (p.h - p.hp + 7) // 8
    l8 = (p.hp + 7) // 8
    md = digest[:ka8]
    idx_tree = int.from_bytes(digest[ka8: ka8 + t8], "big") \
        % (1 << (p.h - p.hp))
    idx_leaf = int.from_bytes(digest[ka8 + t8: ka8 + t8 + l8], "big") \
        % (1 << p.hp)
    return md, idx_tree, idx_leaf


def _m_prime(message: bytes, ctx: bytes) -> bytes:
    return b"\x00" + bytes([len(ctx)]) + ctx + message


# ---------------------------------------------------------------------------
# key objects + keygen + fixture signer
# ---------------------------------------------------------------------------

class SLHDSAPublicKey:
    """SLH-DSA public key: parameter set + (PK.seed ‖ PK.root).

    Duck-typed for the JWK/keyset layer exactly like
    ``MLDSAPublicKey``: ``parameter_set`` routes ``key_matches_alg``
    and the AKP JWK serialization; ``pk`` is the FIPS 205 encoding.
    """

    __slots__ = ("parameter_set", "pk", "pk_seed", "pk_root")

    def __init__(self, parameter_set: str, pk: bytes):
        if parameter_set not in PARAMS:
            raise ValueError(
                f"unknown SLH-DSA parameter set {parameter_set!r}")
        p = PARAMS[parameter_set]
        if len(pk) != p.pk_size:
            raise ValueError(
                f"{p.name} public key must be {p.pk_size} bytes, "
                f"got {len(pk)}")
        self.parameter_set = parameter_set
        self.pk = bytes(pk)
        self.pk_seed = self.pk[: p.n]
        self.pk_root = self.pk[p.n:]

    @property
    def params(self) -> ParameterSet:
        return PARAMS[self.parameter_set]

    def verify(self, signature: bytes, message: bytes) -> bool:
        return py_verify(self, signature, message)


class SLHDSAPrivateKey:
    """Fixture-only deterministic signer (opt_rand = PK.seed, the
    FIPS 205 deterministic variant). Exists to mint KATs, bench
    tokens, and chaos traffic — never production signing."""

    __slots__ = ("public_key", "sk_seed", "sk_prf")

    def __init__(self, pub: SLHDSAPublicKey, sk_seed: bytes,
                 sk_prf: bytes):
        self.public_key = pub
        self.sk_seed = sk_seed
        self.sk_prf = sk_prf

    def sign(self, message: bytes, ctx: bytes = b"") -> bytes:
        if len(ctx) > 255:
            raise ValueError("ctx must be at most 255 bytes")
        pub = self.public_key
        p = pub.params
        n = p.n
        m_prime = _m_prime(message, ctx)
        r = _shake(self.sk_prf + pub.pk_seed + m_prime, n)  # PRF_msg
        digest = _shake(r + pub.pk_seed + pub.pk_root + m_prime, p.m)
        md, idx_tree, idx_leaf = _digest_split(digest, p)
        adrs = ADRS()
        adrs.set_tree(idx_tree)
        adrs.set_type_and_clear(_FORS_TREE)
        adrs.set_keypair(idx_leaf)
        sig = r + _fors_sign(md, self.sk_seed, pub.pk_seed, adrs, p)
        pk_fors = _fors_pk_from_sig(sig[n:], md, pub.pk_seed,
                                    adrs.copy(), p)
        # ht_sign
        node = pk_fors
        itree, ileaf = idx_tree, idx_leaf
        for layer in range(p.d):
            a2 = ADRS()
            a2.set_layer(layer)
            a2.set_tree(itree)
            sig_x = _xmss_sign(node, self.sk_seed, ileaf, pub.pk_seed,
                               a2, p)
            sig += sig_x
            if layer < p.d - 1:
                node = _xmss_pk_from_sig(
                    ileaf, sig_x, node, pub.pk_seed, _layer_adrs(
                        layer, itree), p)
                ileaf = itree & ((1 << p.hp) - 1)
                itree >>= p.hp
        return sig


def _layer_adrs(layer: int, itree: int) -> ADRS:
    a = ADRS()
    a.set_layer(layer)
    a.set_tree(itree)
    return a


def keygen(parameter_set: str,
           seed: bytes) -> Tuple[SLHDSAPrivateKey, SLHDSAPublicKey]:
    """slh_keygen_internal from one 32-byte fixture seed (SK.seed,
    SK.prf, PK.seed expand from it; PK.root is the top XMSS root)."""
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    p = PARAMS[parameter_set]
    n = p.n
    hh = _shake(seed + bytes([p.d, p.k]), 3 * n)
    sk_seed, sk_prf, pk_seed = hh[:n], hh[n: 2 * n], hh[2 * n:]
    adrs = ADRS()
    adrs.set_layer(p.d - 1)
    pk_root = _xmss_node(sk_seed, 0, p.hp, pk_seed, adrs, p)
    pub = SLHDSAPublicKey(parameter_set, pk_seed + pk_root)
    return SLHDSAPrivateKey(pub, sk_seed, sk_prf), pub


# ---------------------------------------------------------------------------
# pure-hashlib host oracle
# ---------------------------------------------------------------------------

def py_verify(pub: SLHDSAPublicKey, signature: bytes,
              message: bytes, ctx: bytes = b"") -> bool:
    """slh_verify (Algorithm 24), entirely host-side hashlib.

    The oracle of last resort AND the engine's parity reference —
    malformed and adversarial inputs included. The only reject gate
    is the signature length; everything else lands in the final root
    compare (the FIPS 205 shape: no malleable encodings to police).
    """
    p = pub.params
    sig = bytes(signature)
    if len(sig) != p.sig_size or len(ctx) > 255:
        return False
    n = p.n
    m_prime = _m_prime(bytes(message), ctx)
    r = sig[:n]
    sig_fors = sig[n: n + p.k * (1 + p.a) * n]
    sig_ht = sig[n + p.k * (1 + p.a) * n:]
    digest = _shake(r + pub.pk_seed + pub.pk_root + m_prime, p.m)
    md, idx_tree, idx_leaf = _digest_split(digest, p)
    adrs = ADRS()
    adrs.set_tree(idx_tree)
    adrs.set_type_and_clear(_FORS_TREE)
    adrs.set_keypair(idx_leaf)
    node = _fors_pk_from_sig(sig_fors, md, pub.pk_seed, adrs, p)
    # ht_verify
    itree, ileaf = idx_tree, idx_leaf
    xmss_bytes = (p.wlen + p.hp) * n
    for layer in range(p.d):
        sig_x = sig_ht[layer * xmss_bytes: (layer + 1) * xmss_bytes]
        node = _xmss_pk_from_sig(ileaf, sig_x, node, pub.pk_seed,
                                 _layer_adrs(layer, itree), p)
        ileaf = itree & ((1 << p.hp) - 1)
        itree >>= p.hp
    return node == pub.pk_root


# ---------------------------------------------------------------------------
# device engine: batched Keccak lanes over pallas_keccak
# ---------------------------------------------------------------------------

def _il_pairs(raw: bytes, n_vals: int) -> np.ndarray:
    """n-byte hash values packed back-to-back -> interleaved lanes
    [n_vals, 2, 2] (16-byte values = 2 u64 lanes each)."""
    from . import pallas_keccak as _kk

    arr = np.frombuffer(raw, np.uint8).view("<u8").reshape(n_vals, 2)
    return _kk.interleave(arr)


_PAD_CONSTS: Dict[int, np.ndarray] = {}


def _tail_pad(total_bytes: int) -> np.ndarray:
    """XOR pad tensor [nb, 25, 2] for a fixed ``total_bytes`` SHAKE256
    absorb (nb = total//136 + 1; pad10*1 always adds a byte)."""
    from . import pallas_keccak as _kk

    hit = _PAD_CONSTS.get(total_bytes)
    if hit is not None:
        return hit
    nb = total_bytes // 136 + 1
    buf = np.zeros(nb * 136, np.uint8)
    buf[total_bytes] = _kk.DOMAIN_SHAKE
    buf[nb * 136 - 1] ^= 0x80
    out = np.zeros((nb, 25, 2), np.uint32)
    out[:, :17] = _kk.interleave(buf.view("<u8")).reshape(nb, 17, 2)
    _PAD_CONSTS[total_bytes] = out
    return out


def _hash_lanes(psd, adrs, msg_lanes):
    """Generic batched F/H/T: SHAKE256(pk_seed ‖ ADRS ‖ msg, 16) on
    interleaved lanes. psd [..., 2, 2] broadcastable, adrs [..., 4, 2],
    msg_lanes [..., L, 2] -> [..., 2, 2]."""
    import jax.numpy as jnp

    from . import pallas_keccak as _kk

    lead = msg_lanes.shape[:-2]
    psd = jnp.broadcast_to(psd, lead + (2, 2))
    adrs = jnp.broadcast_to(adrs, lead + (4, 2))
    content = jnp.concatenate([psd, adrs, msg_lanes], axis=-2)
    nl = content.shape[-2]
    total = 8 * nl
    nb = total // 136 + 1
    fill = nb * 17 - nl
    if fill:
        content = jnp.concatenate(
            [content, jnp.zeros(lead + (fill, 2), jnp.uint32)],
            axis=-2)
    blocks = jnp.zeros(lead + (nb, 25, 2), jnp.uint32)
    blocks = blocks.at[..., :17, :].set(
        content.reshape(lead + (nb, 17, 2)))
    blocks = blocks ^ jnp.asarray(_tail_pad(total))
    return _kk.absorb_fixed(blocks)[..., :2, :]


def _with_hash_addr(adrs, v):
    """ADRS lanes [..., 4, 2] with the dynamic WOTS hash-address word
    (bytes 28-31, value < 16) injected on-device: the value's 4 bits
    land at u64-lane-3 bits 56-59, i.e. interleaved-word bits 28/29."""
    import jax.numpy as jnp

    v = v.astype(jnp.uint32)
    e_add = ((v & 1) << np.uint32(28)) | (((v >> 2) & 1) << np.uint32(29))
    o_add = (((v >> 1) & 1) << np.uint32(28)) \
        | (((v >> 3) & 1) << np.uint32(29))
    delta = jnp.stack([e_add, o_add], axis=-1)[..., None, :]  # [...,1,2]
    zero = jnp.zeros(delta.shape[:-2] + (3, 2), jnp.uint32)
    return adrs ^ jnp.concatenate([zero, delta], axis=-2)


def _digits_from_node(node):
    """WOTS+ message digits [.., 35] (len1 nibbles MSB-first per byte
    + 3 checksum nibbles) from a 16-byte node in interleaved lanes."""
    import jax.numpy as jnp

    from . import pallas_keccak as _kk

    by = _kk.lanes_to_bytes(node).astype(jnp.int32)       # [..., 16]
    digs = jnp.stack([by >> 4, by & 15], axis=-1) \
        .reshape(by.shape[:-1] + (32,))
    csum = jnp.sum(np.int32(W - 1) - digs, axis=-1)
    tail = jnp.stack([csum >> 8, (csum >> 4) & 15, csum & 15], axis=-1)
    return jnp.concatenate([digs, tail], axis=-1)         # [..., 35]


def _slh_core(pk_seed_l, pk_root_l, key_idx, valid,
              fors_sk, fors_adrs, fors_sel, fors_auth, tk_adrs,
              wots_sig, chain_adrs, tlen_adrs,
              xmss_auth, xmss_adrs, xmss_sel):
    """The one-dispatch verify graph: [B] accept bits.

    fors_sk [B,k,2,2]; fors_adrs [B,k,a+1,4,2] (level 0 = leaf F);
    fors_sel [B,k,a]; fors_auth [B,k,a,2,2]; tk_adrs [B,4,2];
    wots_sig [d,B,len,2,2]; chain_adrs [d,B,len,4,2] (hash word 0);
    tlen_adrs [d,B,4,2]; xmss_auth [d,B,hp,2,2]; xmss_adrs
    [d,B,hp,4,2]; xmss_sel [d,B,hp]. Shapes carry every parameter —
    no static arguments needed.
    """
    import jax
    import jax.numpy as jnp

    b, k = fors_sk.shape[0], fors_sk.shape[1]
    a = fors_auth.shape[2]
    hp = xmss_auth.shape[2]
    psd = pk_seed_l[key_idx]                              # [B, 2, 2]
    psd_k = psd[:, None]                                  # [B, 1, 2, 2]

    # FORS: k leaves in parallel, then a auth folds, then T_k.
    node = _hash_lanes(psd_k, fors_adrs[:, :, 0], fors_sk)
    for j in range(a):
        s = fors_sel[:, :, j, None, None]
        left = jnp.where(s, fors_auth[:, :, j], node)
        right = jnp.where(s, node, fors_auth[:, :, j])
        node = _hash_lanes(psd_k, fors_adrs[:, :, j + 1],
                           jnp.concatenate([left, right], axis=-2))
    pk_fors = _hash_lanes(psd, tk_adrs,
                          node.reshape(b, 2 * k, 2))      # [B, 2, 2]

    # Hypertree: scan over the d layers.
    def layer(node, xs):
        w_sig, c_adrs, t_adrs, x_auth, x_adrs, x_sel = xs
        digits = _digits_from_node(node)                  # [B, len]
        vals = w_sig                                      # [B, len, 2, 2]
        for t in range(W - 1):
            active = digits <= np.int32(W - 2 - t)
            adrs_t = _with_hash_addr(c_adrs, digits + np.int32(t))
            nxt = _hash_lanes(psd[:, None], adrs_t, vals)
            vals = jnp.where(active[..., None, None], nxt, vals)
        wlen = vals.shape[1]
        leaf = _hash_lanes(psd, t_adrs, vals.reshape(b, 2 * wlen, 2))
        for lev in range(hp):
            s = x_sel[:, lev, None, None]
            left = jnp.where(s, x_auth[:, lev], leaf)
            right = jnp.where(s, leaf, x_auth[:, lev])
            leaf = _hash_lanes(psd, x_adrs[:, lev],
                               jnp.concatenate([left, right], axis=-2))
        return leaf, None

    root, _ = jax.lax.scan(
        layer, pk_fors,
        (wots_sig, chain_adrs, tlen_adrs, xmss_auth, xmss_adrs,
         xmss_sel))
    ok = (root == pk_root_l[key_idx]).all(axis=(1, 2))
    return ok & valid


_SLH_JIT = None


def _slh_jit():
    global _SLH_JIT
    if _SLH_JIT is None:
        import jax

        _SLH_JIT = jax.jit(_slh_core)
    return _SLH_JIT


class SLHDSAKeyTable:
    """Device-resident SLH-DSA key material for ONE parameter set:
    PK.seed lanes (the first 16 bytes of every F/H/T input) and
    PK.root compare lanes — the key-gather axis, ML-DSA's table shape
    with hashes in place of polynomials."""

    def __init__(self, parameter_set: str,
                 keys: Sequence[SLHDSAPublicKey]):
        import jax.numpy as jnp

        self.parameter_set = parameter_set
        self.params = PARAMS[parameter_set]
        self.keys = list(keys)
        seeds = b"".join(key.pk_seed for key in self.keys)
        roots = b"".join(key.pk_root for key in self.keys)
        self.pk_seed_l = jnp.asarray(_il_pairs(seeds, len(self.keys)))
        self.pk_root_l = jnp.asarray(_il_pairs(roots, len(self.keys)))


class _SLHPrep:
    """Host-side decode of one chunk: sig split, the single H_msg
    SHAKE, index derivation, and EVERY ADRS as interleaved lanes.
    Pure byte shuffling plus one hashlib call per token."""

    __slots__ = ("valid", "key_idx", "fors_sk", "fors_adrs",
                 "fors_sel", "fors_auth", "tk_adrs", "wots_sig",
                 "chain_adrs", "tlen_adrs", "xmss_auth", "xmss_adrs",
                 "xmss_sel", "m")

    def __init__(self, table: SLHDSAKeyTable, sigs: Sequence[bytes],
                 msgs: Sequence[bytes], key_idx: np.ndarray,
                 pad: int):
        from . import pallas_keccak as _kk

        p = table.params
        n, k, a, d, hp, wlen = (p.n, p.k, p.a, p.d, p.hp, p.wlen)
        m = len(sigs)
        self.m = m
        self.valid = np.zeros(pad, bool)
        self.key_idx = np.zeros(pad, np.int32)
        self.key_idx[:m] = np.asarray(key_idx, np.int32)[:m]
        self.fors_sk = np.zeros((pad, k, 2, 2), np.uint32)
        self.fors_auth = np.zeros((pad, k, a, 2, 2), np.uint32)
        self.fors_sel = np.zeros((pad, k, a), bool)
        fors_adrs8 = np.zeros((pad, k, a + 1, 32), np.uint8)
        tk_adrs8 = np.zeros((pad, 32), np.uint8)
        self.wots_sig = np.zeros((d, pad, wlen, 2, 2), np.uint32)
        chain_adrs8 = np.zeros((d, pad, wlen, 32), np.uint8)
        tlen_adrs8 = np.zeros((d, pad, 32), np.uint8)
        self.xmss_auth = np.zeros((d, pad, hp, 2, 2), np.uint32)
        xmss_adrs8 = np.zeros((d, pad, hp, 32), np.uint8)
        self.xmss_sel = np.zeros((d, pad, hp), bool)
        xmss_bytes = (wlen + hp) * n

        for i in range(m):
            sig = bytes(sigs[i])
            if len(sig) != p.sig_size:
                continue
            self.valid[i] = True
            key = table.keys[int(self.key_idx[i])]
            r = sig[:n]
            sig_fors = sig[n: n + k * (1 + a) * n]
            sig_ht = sig[n + k * (1 + a) * n:]
            digest = _shake(r + key.pk_seed + key.pk_root
                            + _m_prime(bytes(msgs[i]), b""), p.m)
            md, idx_tree, idx_leaf = _digest_split(digest, p)
            indices = base_2b(md, a, k)

            adrs = ADRS()
            adrs.set_tree(idx_tree)
            adrs.set_type_and_clear(_FORS_TREE)
            adrs.set_keypair(idx_leaf)
            for t in range(k):
                off = t * (1 + a) * n
                self.fors_sk[i, t] = _il_pairs(
                    sig_fors[off: off + n], 1)[0]
                self.fors_auth[i, t] = _il_pairs(
                    sig_fors[off + n: off + (1 + a) * n], a)
                idx = indices[t]
                adrs.set_tree_height(0)
                adrs.set_tree_index(t * (1 << a) + idx)
                fors_adrs8[i, t, 0] = np.frombuffer(adrs.bytes(),
                                                    np.uint8)
                ti = t * (1 << a) + idx
                for j in range(a):
                    self.fors_sel[i, t, j] = bool((idx >> j) & 1)
                    ti //= 2
                    adrs.set_tree_height(j + 1)
                    adrs.set_tree_index(ti)
                    fors_adrs8[i, t, j + 1] = np.frombuffer(
                        adrs.bytes(), np.uint8)
            tk = adrs.copy()
            tk.set_type_and_clear(_FORS_ROOTS)
            tk.set_keypair(idx_leaf)
            tk_adrs8[i] = np.frombuffer(tk.bytes(), np.uint8)

            itree, ileaf = idx_tree, idx_leaf
            for layer in range(d):
                sig_x = sig_ht[layer * xmss_bytes:
                               (layer + 1) * xmss_bytes]
                self.wots_sig[layer, i] = _il_pairs(
                    sig_x[: wlen * n], wlen)
                self.xmss_auth[layer, i] = _il_pairs(
                    sig_x[wlen * n:], hp)
                base = ADRS()
                base.set_layer(layer)
                base.set_tree(itree)
                base.set_type_and_clear(_WOTS_HASH)
                base.set_keypair(ileaf)
                for c in range(wlen):
                    base.set_chain(c)
                    chain_adrs8[layer, i, c] = np.frombuffer(
                        base.bytes(), np.uint8)
                tl = base.copy()
                tl.set_type_and_clear(_WOTS_PK)
                tl.set_keypair(ileaf)
                tlen_adrs8[layer, i] = np.frombuffer(tl.bytes(),
                                                     np.uint8)
                tr = base.copy()
                tr.set_type_and_clear(_TREE)
                ti = ileaf
                for lev in range(hp):
                    self.xmss_sel[layer, i, lev] = bool(
                        (ileaf >> lev) & 1)
                    ti //= 2
                    tr.set_tree_height(lev + 1)
                    tr.set_tree_index(ti)
                    xmss_adrs8[layer, i, lev] = np.frombuffer(
                        tr.bytes(), np.uint8)
                ileaf = itree & ((1 << hp) - 1)
                itree >>= hp

        def il_adrs(arr8):
            return _kk.interleave(
                np.ascontiguousarray(arr8).view("<u8"))

        self.fors_adrs = il_adrs(fors_adrs8)
        self.tk_adrs = il_adrs(tk_adrs8)
        self.chain_adrs = il_adrs(chain_adrs8)
        self.tlen_adrs = il_adrs(tlen_adrs8)
        self.xmss_adrs = il_adrs(xmss_adrs8)

    def arrays(self) -> tuple:
        return (self.key_idx, self.valid, self.fors_sk,
                self.fors_adrs, self.fors_sel, self.fors_auth,
                self.tk_adrs, self.wots_sig, self.chain_adrs,
                self.tlen_adrs, self.xmss_auth, self.xmss_adrs,
                self.xmss_sel)


def verify_slhdsa_pending(table: SLHDSAKeyTable,
                          sigs: Sequence[bytes],
                          msgs: Sequence[bytes],
                          key_idx: np.ndarray,
                          pad: Optional[int] = None, mesh=None):
    """Batched two-phase verify: host decode + ONE device dispatch
    now; the returned ``fin()`` materializes [pad] bool verdicts.

    Wrong-length signatures never touch the device and finish False —
    the exact verdicts ``py_verify`` produces (length is SLH-DSA's
    only non-root reject gate)."""
    if pad is None:
        # pow-2 bucket with a 16-row floor: every distinct pad is a
        # separate XLA compile of the whole hash forest (~10s on this
        # host), so ad-hoc batch sizes must share shapes.
        pad = 16
        while pad < len(sigs):
            pad *= 2
    prep = _SLHPrep(table, sigs, msgs, key_idx, pad)
    if prep.valid.any():
        import jax

        arrs = prep.arrays()
        if mesh is not None:
            from ..parallel.place import shard_batch

            # batch axis is axis 0 for the FORS arrays and axis 1 for
            # the layer-major HT arrays — shard only the former, let
            # the scan xs replicate (correct either way; the batch-DP
            # split of the heavy lanes is what matters).
            put = [shard_batch(mesh, a) if a.shape[0] == pad
                   else jax.device_put(a) for a in arrs]
        else:
            put = [jax.device_put(a) for a in arrs]
        out = _slh_jit()(table.pk_seed_l, table.pk_root_l, *put)
    else:
        out = None

    def fin() -> np.ndarray:
        if out is None:
            return np.zeros(pad, bool)
        return np.asarray(out)

    return fin


def verify_slhdsa_batch(table: SLHDSAKeyTable, sigs: Sequence[bytes],
                        msgs: Sequence[bytes],
                        key_idx: np.ndarray, mesh=None) -> np.ndarray:
    """[N] bool verdicts for one SLH-DSA bucket (blocking)."""
    return verify_slhdsa_pending(table, sigs, msgs, key_idx,
                                 mesh=mesh)()
