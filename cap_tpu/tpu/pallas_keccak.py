"""Batched Keccak-f[1600] + SHAKE-128/256 device lanes (FIPS 202).

The post-quantum verify families are SHAKE-bound: ML-DSA's μ/c̃
absorb-squeeze ran on the host per token (the last per-token host hash
in any packed path), and SLH-DSA verify is ~2-6k Keccak permutations
per signature — *pure hash*, nothing else. This module makes Keccak a
batch-lane workload like everything else in ``cap_tpu/tpu``:

- **state layout**: each 64-bit Keccak lane rides as a **uint32
  bit-interleaved pair** — word 0 holds the even-indexed bits, word 1
  the odd-indexed bits — so a 64-bit rotation is two independent
  32-bit rotations (the classic 32-bit Keccak trick), and no int64
  ever appears (TPUs have no 64-bit integer units; the same posture
  as the NTT's 16-bit-limb Montgomery). A batch is ``[..., 25, 2]``
  uint32; XOR/AND/NOT are interleaving-transparent.
- ``f1600`` is the jitted jnp permutation (``lax.fori_loop`` over the
  24 rounds, ρ/π unrolled per lane); ``f1600_pallas`` runs the whole
  permutation as ONE Pallas kernel on a ``[50, L]`` VMEM tile (rows =
  25 even + 25 odd planes) in the ``pallas_madd``/``redc``/``edw``
  house pattern, with interpret-mode fallback on CPU. ``permute``
  dispatches between them via :func:`enabled`.
- absorb/squeeze drivers: the HOST does byte-level padding only
  (cheap, branchy, variable-length — never a hash); blocks ship as
  pre-interleaved lane tensors and the device runs the masked
  per-token block loop, so tokens of different lengths share one
  fixed-shape graph.

``f1600_ref``/``shake128_ref``/``shake256_ref`` are the numpy uint64
host references — pinned against stdlib ``hashlib.shake_128/256`` on
arbitrary absorb/squeeze lengths by tests/test_pallas_keccak.py (the
``ntt_ref`` contract, extended), and the bit-equality reference for
both device paths. They also back the numpy-batched fixture signer in
``slhdsa.py``.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

# jax is imported inside the device entry points: the numpy reference
# must stay importable on accelerator-less hosts (same lazy-jax stance
# as ntt.py).

RATE_SHAKE128 = 168               # bytes; 21 lanes
RATE_SHAKE256 = 136               # bytes; 17 lanes
DOMAIN_SHAKE = 0x1F               # FIPS 202 SHAKE domain + pad10*1 head


def _gen_round_constants() -> np.ndarray:
    """The 24 ι round constants from the rc(t) LFSR (FIPS 202 §3.2.5)
    — generated, not transcribed, so they cannot be mistyped."""
    def rc_bits():
        r = 1
        while True:
            yield r & 1
            r <<= 1
            if r & 0x100:
                r ^= 0x171
    bits = rc_bits()
    out = []
    for _ in range(24):
        rc = 0
        for j in range(7):
            if next(bits):
                rc |= 1 << ((1 << j) - 1)
        out.append(rc)
    return np.array(out, np.uint64)


def _gen_rho_offsets() -> np.ndarray:
    """ρ rotation offsets per flat lane x+5y (FIPS 202 §3.2.2),
    generated from the (t+1)(t+2)/2 walk."""
    r = np.zeros(25, np.int64)
    x, y = 1, 0
    for t in range(24):
        r[x + 5 * y] = ((t + 1) * (t + 2) // 2) % 64
        x, y = y, (2 * x + 3 * y) % 5
    return r


RC64 = _gen_round_constants()
RHO = _gen_rho_offsets()
def _gen_pi() -> np.ndarray:
    # π: input lane x+5y lands at output flat lane y + 5*((2x+3y)%5).
    dest = np.zeros(25, np.int64)
    for x in range(5):
        for y in range(5):
            dest[x + 5 * y] = y + 5 * ((2 * x + 3 * y) % 5)
    return dest


PI_DEST = _gen_pi()
# PI_SRC[l'] = the input lane that lands at output lane l'.
PI_SRC = np.zeros(25, np.int64)
PI_SRC[PI_DEST] = np.arange(25)


# ---------------------------------------------------------------------------
# numpy uint64 reference (exact; the oracle-side transform)
# ---------------------------------------------------------------------------

def _rotl64(v: np.ndarray, r: int) -> np.ndarray:
    if r == 0:
        return v
    return (v << np.uint64(r)) | (v >> np.uint64(64 - r))


def f1600_ref(state: np.ndarray) -> np.ndarray:
    """Keccak-f[1600] on uint64 lanes ``[..., 25]`` (flat index x+5y)."""
    a = np.asarray(state, np.uint64).copy()
    for rc in RC64:
        # θ
        c = a[..., 0:5].copy()
        for y in range(1, 5):
            c ^= a[..., 5 * y: 5 * y + 5]
        d = np.empty_like(c)
        for x in range(5):
            d[..., x] = c[..., (x - 1) % 5] ^ _rotl64(c[..., (x + 1) % 5], 1)
        for y in range(5):
            a[..., 5 * y: 5 * y + 5] ^= d
        # ρ + π
        b = np.empty_like(a)
        for l in range(25):
            b[..., PI_DEST[l]] = _rotl64(a[..., l], int(RHO[l]))
        # χ
        for y in range(5):
            row = b[..., 5 * y: 5 * y + 5]
            a[..., 5 * y: 5 * y + 5] = row ^ (
                ~np.roll(row, -1, axis=-1) & np.roll(row, -2, axis=-1))
        # ι
        a[..., 0] ^= rc
    return a


def _shake_ref(data: bytes, rate: int, outlen: int) -> bytes:
    """SHAKE sponge on the numpy reference permutation."""
    msg = bytearray(data)
    msg.append(DOMAIN_SHAKE)
    while len(msg) % rate:
        msg.append(0)
    msg[-1] ^= 0x80
    state = np.zeros(25, np.uint64)
    nl = rate // 8
    for off in range(0, len(msg), rate):
        block = np.frombuffer(bytes(msg[off: off + rate]),
                              np.uint8).view("<u8")
        state[:nl] ^= block
        state = f1600_ref(state)
    out = bytearray()
    while len(out) < outlen:
        out += state[:nl].tobytes()[:rate]
        if len(out) < outlen:
            state = f1600_ref(state)
    return bytes(out[:outlen])


def shake128_ref(data: bytes, outlen: int) -> bytes:
    return _shake_ref(data, RATE_SHAKE128, outlen)


def shake256_ref(data: bytes, outlen: int) -> bytes:
    return _shake_ref(data, RATE_SHAKE256, outlen)


# ---------------------------------------------------------------------------
# bit interleaving (host numpy; uint64 <-> uint32 even/odd pairs)
# ---------------------------------------------------------------------------

def _compress_even_u64(x: np.ndarray) -> np.ndarray:
    """Gather the even-indexed bits of uint64 lanes into the low 32."""
    m = np.uint64
    x = x & m(0x5555555555555555)
    x = (x | (x >> m(1))) & m(0x3333333333333333)
    x = (x | (x >> m(2))) & m(0x0F0F0F0F0F0F0F0F)
    x = (x | (x >> m(4))) & m(0x00FF00FF00FF00FF)
    x = (x | (x >> m(8))) & m(0x0000FFFF0000FFFF)
    x = (x | (x >> m(16))) & m(0x00000000FFFFFFFF)
    return x.astype(np.uint32)


def _spread_u32_to_even_u64(x: np.ndarray) -> np.ndarray:
    """Inverse of :func:`_compress_even_u64`: u32 -> even bits of u64."""
    m = np.uint64
    x = x.astype(np.uint64)
    x = (x | (x << m(16))) & m(0x0000FFFF0000FFFF)
    x = (x | (x << m(8))) & m(0x00FF00FF00FF00FF)
    x = (x | (x << m(4))) & m(0x0F0F0F0F0F0F0F0F)
    x = (x | (x << m(2))) & m(0x3333333333333333)
    x = (x | (x << m(1))) & m(0x5555555555555555)
    return x


def interleave(lanes64: np.ndarray) -> np.ndarray:
    """uint64 lanes ``[...]`` -> interleaved uint32 pairs ``[..., 2]``
    (``[..., 0]`` = even bits, ``[..., 1]`` = odd bits)."""
    lanes64 = np.asarray(lanes64, np.uint64)
    e = _compress_even_u64(lanes64)
    o = _compress_even_u64(lanes64 >> np.uint64(1))
    return np.stack([e, o], axis=-1)


def deinterleave(il: np.ndarray) -> np.ndarray:
    """Interleaved uint32 pairs ``[..., 2]`` -> uint64 lanes ``[...]``."""
    il = np.asarray(il, np.uint32)
    e = _spread_u32_to_even_u64(il[..., 0])
    o = _spread_u32_to_even_u64(il[..., 1])
    return e | (o << np.uint64(1))


RC_IL = interleave(RC64)                                  # [24, 2]
# ι as a one-hot XOR mask over the full state (broadcasts in the
# fori_loop body without dynamic-update ops).
RC_ONEHOT = np.zeros((24, 25, 2), np.uint32)
RC_ONEHOT[:, 0, :] = RC_IL

# 64-bit rotation in the interleaved domain: even r -> both words
# rotate by r/2; odd r -> the words swap roles, the (new) even word
# rotates one extra step. Precomputed per lane for the ρ offsets.
_RHO_SWAP = (RHO % 2).astype(bool)
_RHO_RE = np.where(_RHO_SWAP, (RHO + 1) // 2, RHO // 2)   # rot for E'
_RHO_RO = RHO // 2                                        # rot for O'


# ---------------------------------------------------------------------------
# jnp permutation on interleaved lanes (the CPU/XLA device path)
# ---------------------------------------------------------------------------

def _rotl32(w, s: int):
    if s == 0:
        return w
    return (w << np.uint32(s)) | (w >> np.uint32(32 - s))


# ρ/π fused for the vectorized jnp path: output lane lp takes input
# lane PI_SRC[lp] rotated by RHO[PI_SRC[lp]] — rotation amounts and
# the odd-rotation word swap indexed per OUTPUT lane.
_PI_RE = _RHO_RE[PI_SRC].astype(np.uint32)
_PI_RO = _RHO_RO[PI_SRC].astype(np.uint32)
_PI_SWAP = _RHO_SWAP[PI_SRC]


def _rotv(w, s):
    """Per-element uint32 rotate-left (s in [0, 32), vector amounts)."""
    import jax.numpy as jnp

    return jnp.where(s == 0, w,
                     (w << s) | (w >> ((np.uint32(32) - s)
                                       & np.uint32(31))))


def _round_il(a, rc_onehot):
    """One Keccak round on ``[..., 25, 2]`` uint32 interleaved lanes
    (fully vectorized across lanes — per-lane rotation amounts ride as
    element-wise shift vectors, no python lane loop)."""
    import jax.numpy as jnp

    lead = a.shape[:-2]
    a5 = a.reshape(lead + (5, 5, 2))          # [..., y, x, 2]
    c = a5[..., 0, :, :] ^ a5[..., 1, :, :] ^ a5[..., 2, :, :] \
        ^ a5[..., 3, :, :] ^ a5[..., 4, :, :]             # [..., x, 2]
    cm1 = jnp.roll(c, 1, axis=-2)
    cp1 = jnp.roll(c, -1, axis=-2)
    # rot64 by 1 (odd): E' = rotl32(O, 1), O' = E
    cp1r = jnp.stack([_rotl32(cp1[..., 1], 1), cp1[..., 0]], axis=-1)
    d = cm1 ^ cp1r                                        # [..., x, 2]
    a = (a5 ^ d[..., None, :, :]).reshape(lead + (25, 2))
    # ρ + π in one gather + two vector rotates
    g = jnp.take(a, jnp.asarray(PI_SRC), axis=-2)         # [..., 25, 2]
    ge, go = g[..., 0], g[..., 1]
    re = jnp.asarray(_PI_RE)
    ro = jnp.asarray(_PI_RO)
    swap = jnp.asarray(_PI_SWAP)
    be = jnp.where(swap, _rotv(go, re), _rotv(ge, re))
    bo = jnp.where(swap, _rotv(ge, ro), _rotv(go, ro))
    b5 = jnp.stack([be, bo], axis=-1).reshape(lead + (5, 5, 2))
    a = (b5 ^ (~jnp.roll(b5, -1, axis=-2) & jnp.roll(b5, -2, axis=-2))) \
        .reshape(lead + (25, 2))
    return a ^ rc_onehot


def f1600(state):
    """Keccak-f[1600] on ``[..., 25, 2]`` uint32 interleaved lanes
    (jnp; jit-safe — the 24 rounds ride a ``fori_loop``)."""
    import jax
    import jax.numpy as jnp

    rc = jnp.asarray(RC_ONEHOT)

    def body(i, a):
        return _round_il(a, rc[i])

    return jax.lax.fori_loop(0, 24, body, state)


# ---------------------------------------------------------------------------
# Pallas kernel: the whole permutation on one [50, L] VMEM tile
# ---------------------------------------------------------------------------

_TILE = int(os.environ.get("CAP_TPU_KECCAK_TILE", 256))   # lanes/step


def enabled() -> bool:
    """Fused Pallas Keccak kernel: CAP_TPU_PALLAS_KECCAK=1/0 overrides.

    Default ON for accelerator backends (the Mosaic target the house
    kernels compile for); CPU stays on the jnp path — interpret mode
    is a correctness harness, not a fast path (docs/PERF.md; the
    bench_stages kernel rows publish the honest CPU A/B).
    """
    v = os.environ.get("CAP_TPU_PALLAS_KECCAK")
    if v is not None:
        return v not in ("0", "false", "no")
    import jax

    return jax.default_backend() == "tpu"


def _round_planes(planes, rc2):
    """One round on a [50, T] plane stack (rows 0-24 even words, rows
    25-49 odd); ``rc2`` is the round's interleaved ι constant [1, 2].
    Static row slices only (the Mosaic gather rule, as in
    pallas_madd's cpA handling); shared by the kernel's round loop."""
    import jax.numpy as jnp

    e = [planes[l: l + 1, :] for l in range(25)]
    o = [planes[25 + l: 26 + l, :] for l in range(25)]
    ce = [e[x] ^ e[x + 5] ^ e[x + 10] ^ e[x + 15] ^ e[x + 20]
          for x in range(5)]
    co = [o[x] ^ o[x + 5] ^ o[x + 10] ^ o[x + 15] ^ o[x + 20]
          for x in range(5)]
    de = [ce[(x - 1) % 5] ^ _rotl32(co[(x + 1) % 5], 1)
          for x in range(5)]
    do = [co[(x - 1) % 5] ^ ce[(x + 1) % 5] for x in range(5)]
    e = [e[l] ^ de[l % 5] for l in range(25)]
    o = [o[l] ^ do[l % 5] for l in range(25)]
    be: List = [None] * 25
    bo: List = [None] * 25
    for l in range(25):
        ee, oo = e[l], o[l]
        if _RHO_SWAP[l]:
            ne = _rotl32(oo, int(_RHO_RE[l]))
            no = _rotl32(ee, int(_RHO_RO[l]))
        else:
            ne = _rotl32(ee, int(_RHO_RE[l]))
            no = _rotl32(oo, int(_RHO_RO[l]))
        be[int(PI_DEST[l])] = ne
        bo[int(PI_DEST[l])] = no
    e = [be[l] ^ (~be[5 * (l // 5) + (l + 1) % 5]
                  & be[5 * (l // 5) + (l + 2) % 5]) for l in range(25)]
    o = [bo[l] ^ (~bo[5 * (l // 5) + (l + 1) % 5]
                  & bo[5 * (l // 5) + (l + 2) % 5]) for l in range(25)]
    e[0] = e[0] ^ rc2[0:1, 0:1]
    o[0] = o[0] ^ rc2[0:1, 1:2]
    return jnp.concatenate(e + o, axis=0)


def _f1600_kernel(s_ref, rc_ref, o_ref):
    """The 24 rounds as an in-kernel ``fori_loop`` on a [50, T] VMEM
    tile — one compact round body instead of a 24x-unrolled graph
    (the unrolled form compiled for minutes in interpret mode)."""
    import jax

    rc = rc_ref[:]                       # [24, 2] value

    def body(rnd, planes):
        rc2 = jax.lax.dynamic_slice(rc, (rnd, 0), (1, 2))
        return _round_planes(planes, rc2)

    o_ref[:] = jax.lax.fori_loop(0, 24, body, s_ref[:])


def _f1600_call(planes, interpret: bool):
    import jax
    import jax.numpy as jnp
    from functools import partial
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    @partial(jax.jit, static_argnames=("interpret",))
    def call(planes, rc, interpret: bool):
        n = planes.shape[1]
        grid = n // _TILE
        spec = pl.BlockSpec((50, _TILE), lambda i: (0, i),
                            memory_space=pltpu.VMEM)
        rc_spec = pl.BlockSpec((24, 2), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
        return pl.pallas_call(
            _f1600_kernel,
            out_shape=jax.ShapeDtypeStruct((50, n), jnp.uint32),
            grid=(grid,),
            in_specs=[spec, rc_spec],
            out_specs=spec,
            interpret=interpret,
        )(planes, rc)

    return call(planes, jnp.asarray(RC_IL), interpret)


def f1600_pallas(state, interpret: Optional[bool] = None):
    """Pallas-kernel permutation on ``[..., 25, 2]`` interleaved lanes
    — bit-identical to :func:`f1600` (pinned interpret-mode on CPU by
    tests + make pallas-smoke). Lanes fold onto the kernel's [50, L]
    plane layout; L pads to the tile size."""
    import jax.numpy as jnp

    if interpret is None:
        import jax

        interpret = jax.default_backend() != "tpu"
    lead = state.shape[:-2]
    n = 1
    for s in lead:
        n *= s
    flat = state.reshape((n, 25, 2))
    planes = jnp.concatenate([flat[:, :, 0].T, flat[:, :, 1].T], axis=0)
    pad = (-n) % _TILE
    if pad:
        planes = jnp.pad(planes, ((0, 0), (0, pad)))
    out = _f1600_call(planes, interpret)[:, :n]
    return jnp.stack([out[:25].T, out[25:].T], axis=-1).reshape(
        lead + (25, 2))


def permute(state, interpret: Optional[bool] = None):
    """The permutation the device drivers call: the Pallas kernel when
    :func:`enabled`, the jnp graph otherwise. Bit-identical either
    way."""
    if enabled():
        return f1600_pallas(state, interpret=interpret)
    return f1600(state)


# ---------------------------------------------------------------------------
# host packing + device absorb/squeeze drivers
# ---------------------------------------------------------------------------

def pad_message(data: bytes, rate: int) -> bytes:
    """SHAKE pad10*1 with the 0x1F domain: whole rate-blocks out."""
    msg = bytearray(data)
    msg.append(DOMAIN_SHAKE)
    while len(msg) % rate:
        msg.append(0)
    msg[-1] ^= 0x80
    return bytes(msg)


def pack_blocks(msgs: Sequence[bytes], rate: int,
                min_blocks: int = 1) -> Tuple[np.ndarray, np.ndarray]:
    """Pad + interleave a batch of variable-length messages.

    Returns ``(blocks [B, NB, 25, 2] uint32, nblk [B] int32)`` where
    NB = max(ceil((len+1)/rate)) over the batch (at least
    ``min_blocks``); capacity lanes and blocks past a token's count
    are zero. The HOST does only byte shuffling here — no hashing.
    """
    nl = rate // 8
    padded = [pad_message(m, rate) for m in msgs]
    nblk = np.array([len(p) // rate for p in padded], np.int32)
    nb = max(int(nblk.max()) if len(padded) else 1, min_blocks)
    out = np.zeros((len(padded), nb, 25, 2), np.uint32)
    for i, p in enumerate(padded):
        lanes = np.frombuffer(p, np.uint8).view("<u8").reshape(-1, nl)
        out[i, : lanes.shape[0], :nl] = interleave(lanes)
    return out, nblk


def absorb(blocks, nblk):
    """Masked batched absorb: ``blocks`` [..., NB, 25, 2] uint32 (from
    :func:`pack_blocks`, already on device or host), ``nblk`` [...]
    int32. Lanes finish at their own block count and freeze — the
    per-lane select that lets one fixed-shape graph serve a whole
    mixed-length batch. Returns the final states [..., 25, 2]."""
    import jax.numpy as jnp

    state = jnp.zeros(blocks.shape[:-3] + (25, 2), jnp.uint32)
    for blk in range(blocks.shape[-3]):
        nxt = permute(state ^ blocks[..., blk, :, :])
        live = (nblk > blk)[..., None, None]
        state = jnp.where(live, nxt, state)
    return state


def absorb_fixed(blocks):
    """Absorb with a UNIFORM block count (no mask): ``blocks``
    [..., NB, 25, 2] where every lane uses all NB blocks — the
    fixed-length hash path (w1 encode, tree nodes, WOTS chains)."""
    import jax.numpy as jnp

    state = jnp.zeros(blocks.shape[:-3] + (25, 2), jnp.uint32)
    for blk in range(blocks.shape[-3]):
        state = permute(state ^ blocks[..., blk, :, :])
    return state


def squeeze_lanes(state, rate: int, n_blocks: int):
    """``n_blocks`` squeeze blocks of interleaved lanes from absorbed
    states [B, 25, 2] -> [B, n_blocks * rate//8, 2]."""
    import jax.numpy as jnp

    nl = rate // 8
    outs = [state[..., :nl, :]]
    for _ in range(n_blocks - 1):
        state = permute(state)
        outs.append(state[..., :nl, :])
    return jnp.concatenate(outs, axis=-2)


def lanes_to_bytes(lanes):
    """Interleaved lanes [..., L, 2] -> bytes [..., L*8] uint32-valued
    (each entry in [0, 256)) — the device-side deinterleave, built
    from 16->32 bit spreads so no int64 appears."""
    import jax.numpy as jnp

    def spread16(x):
        x = x & np.uint32(0xFFFF)
        x = (x | (x << np.uint32(8))) & np.uint32(0x00FF00FF)
        x = (x | (x << np.uint32(4))) & np.uint32(0x0F0F0F0F)
        x = (x | (x << np.uint32(2))) & np.uint32(0x33333333)
        x = (x | (x << np.uint32(1))) & np.uint32(0x55555555)
        return x

    e, o = lanes[..., 0], lanes[..., 1]
    lo = spread16(e) | (spread16(o) << np.uint32(1))
    hi = spread16(e >> np.uint32(16)) | \
        (spread16(o >> np.uint32(16)) << np.uint32(1))
    w = jnp.stack([lo, hi], axis=-1)          # [..., L, 2] u32 (lo,hi)
    shifts = np.arange(4, dtype=np.uint32) * 8
    by = (w[..., None] >> shifts) & np.uint32(0xFF)
    return by.reshape(by.shape[:-3] + (-1,))


def bits_to_lanes(bits):
    """Little-endian bit tensor [..., L*64] (values 0/1 uint32) ->
    interleaved lanes [..., L, 2]: even/odd bits fold directly into
    the two words, skipping the byte stage entirely."""
    import jax.numpy as jnp

    lead = bits.shape[:-1]
    nl = bits.shape[-1] // 64
    v = bits.reshape(lead + (nl, 32, 2)).astype(jnp.uint32)
    shifts = np.arange(32, dtype=np.uint32)
    e = jnp.sum(v[..., 0] << shifts, axis=-1, dtype=jnp.uint32)
    o = jnp.sum(v[..., 1] << shifts, axis=-1, dtype=jnp.uint32)
    return jnp.stack([e, o], axis=-1)
