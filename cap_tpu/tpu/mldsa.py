"""Batched ML-DSA (FIPS 204, Dilithium) signature verification.

The post-quantum verify family (ROADMAP open item #2): ML-DSA verify
is NTT- and SHAKE-dominated — polynomial arithmetic over Z_8380417
that maps directly onto the repo's packed batch lanes, plus Keccak
absorption that is cheap, branchy, and variable-length, i.e. exactly
the work the RSA/EC engines already leave on the host (the SHA-prep
split). The same split applies here:

- **host** (stdlib ``hashlib.shake_128/256``): matrix expansion from
  ρ (cached per key), tr/μ hashing, SampleInBall(c̃), signature
  decode + range/hint validity checks, the final w1Encode + μ/c̃
  hash compare;
- **device** (``ntt.py`` uint32 Montgomery lanes): NTT(z), NTT(c),
  the Â∘ẑ − ĉ∘(t̂1·2^d) ring accumulation against device-resident
  per-key tables (the key-gather axis), inverse NTT, and the
  Decompose/UseHint recomposition to w1 — the ~70%-of-verify
  arithmetic the GPU Dilithium engine in PAPERS.md batches the same
  way.

``py_verify`` is the pure-integer host oracle (numpy int64 over
``ntt.ntt_ref``; no jax, no third-party crypto): the availability
contract's fallback and the bit-exactness reference for the device
graph, exactly like ``ec._py_verify_one``. Keygen and a deterministic
signer exist ONLY to produce fixtures (KAT vectors, bench/chaos
tokens) — the framework's job is verification.

Nothing in this module's host path imports jax; the device entry
points pull it lazily, so JWK parsing and the CPU oracle work on
crypto-less, accelerator-less hosts.
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import ntt as _ntt

Q = _ntt.Q
N = 256
D = 13                               # dropped t bits (all parameter sets)

# Host SHAKE accounting: every hashlib absorb-squeeze the module does
# bumps this counter when telemetry records — the fused packed path's
# "zero per-token host SHAKE" contract is pinned against it
# (tests/test_mldsa_fused.py). Key-scoped hashing (tr, ExpandA at
# table build) still counts; it is per KEY, not per token.
HOST_SHAKE_COUNTER = "mldsa.host_shake_calls"


class ParameterSet:
    """One FIPS 204 parameter set (Table 1) plus derived sizes."""

    __slots__ = ("name", "k", "l", "eta", "tau", "lam", "gamma1",
                 "gamma2", "omega", "beta", "z_bits", "w1_bits",
                 "pk_size", "sig_size", "m")

    def __init__(self, name: str, k: int, l: int, eta: int, tau: int,
                 lam: int, gamma1: int, gamma2: int, omega: int):
        self.name = name
        self.k, self.l = k, l
        self.eta, self.tau, self.lam = eta, tau, lam
        self.gamma1, self.gamma2, self.omega = gamma1, gamma2, omega
        self.beta = tau * eta
        self.z_bits = 1 + (gamma1 - 1).bit_length()       # 18 or 20
        self.m = (Q - 1) // (2 * gamma2)                  # 44 or 16
        self.w1_bits = (self.m - 1).bit_length()          # 6 or 4
        self.pk_size = 32 + 32 * 10 * k
        self.sig_size = lam // 4 + l * 32 * self.z_bits + omega + k


PARAMS: Dict[str, ParameterSet] = {
    "ML-DSA-44": ParameterSet("ML-DSA-44", 4, 4, 2, 39, 128,
                              1 << 17, (Q - 1) // 88, 80),
    "ML-DSA-65": ParameterSet("ML-DSA-65", 6, 5, 4, 49, 192,
                              1 << 19, (Q - 1) // 32, 55),
    "ML-DSA-87": ParameterSet("ML-DSA-87", 8, 7, 2, 60, 256,
                              1 << 19, (Q - 1) // 32, 75),
}

MLDSA_ALGS = tuple(PARAMS)           # the JOSE alg names ARE the set names


def _count_host_shake() -> None:
    from .. import telemetry

    if telemetry.active() is not None:
        telemetry.count(HOST_SHAKE_COUNTER)


def _shake256(data: bytes, outlen: int) -> bytes:
    _count_host_shake()
    return hashlib.shake_256(data).digest(outlen)


def _shake128(data: bytes, outlen: int) -> bytes:
    _count_host_shake()
    return hashlib.shake_128(data).digest(outlen)


# ---------------------------------------------------------------------------
# bit packing (FIPS 204 §7.1: IntegerToBits is little-endian, bytes
# fill LSB-first — numpy's bitorder="little")
# ---------------------------------------------------------------------------

def bitpack(arr: np.ndarray, bits: int) -> np.ndarray:
    """[..., n] non-negative ints < 2^bits → uint8 [..., n·bits/8]."""
    a = np.asarray(arr, np.int64)
    b = ((a[..., :, None] >> np.arange(bits)) & 1).astype(np.uint8)
    flat = b.reshape(a.shape[:-1] + (a.shape[-1] * bits,))
    return np.packbits(flat, axis=-1, bitorder="little")


def bitunpack(buf: np.ndarray, bits: int, n: int) -> np.ndarray:
    """uint8 [..., n·bits/8] → int64 [..., n] (inverse of bitpack)."""
    u = np.asarray(buf, np.uint8)
    b = np.unpackbits(u, axis=-1, bitorder="little")[..., : n * bits]
    b = b.reshape(u.shape[:-1] + (n, bits)).astype(np.int64)
    return (b << np.arange(bits)).sum(axis=-1)


# ---------------------------------------------------------------------------
# host sampling (SHAKE expansion; all rejection loops grow-and-retry
# because hashlib cannot squeeze incrementally — the retry re-absorbs
# the same prefix, so outputs are identical to a streaming squeeze)
# ---------------------------------------------------------------------------

def _rej_ntt_poly(seed: bytes) -> np.ndarray:
    """RejNTTPoly (Alg 30): 23-bit rejection sampling from SHAKE128."""
    outlen = 1024                    # 341 triples ≈ 256/0.999 needed
    while True:
        buf = np.frombuffer(_shake128(seed, outlen), np.uint8)
        t = buf[: len(buf) - len(buf) % 3].reshape(-1, 3).astype(np.int64)
        vals = t[:, 0] | (t[:, 1] << 8) | ((t[:, 2] & 0x7F) << 16)
        vals = vals[vals < Q]
        if len(vals) >= N:
            return vals[:N]
        outlen *= 2


def expand_a(rho: bytes, p: ParameterSet) -> np.ndarray:
    """ExpandA (Alg 32): the NTT-domain [k, l, 256] public matrix."""
    out = np.empty((p.k, p.l, N), np.int64)
    for r in range(p.k):
        for s in range(p.l):
            out[r, s] = _rej_ntt_poly(rho + bytes([s, r]))
    return out


def _rej_bounded_poly(seed: bytes, eta: int) -> np.ndarray:
    """RejBoundedPoly (Alg 31): centered coefficients in [-η, η]."""
    outlen = 192
    while True:
        buf = np.frombuffer(_shake256(seed, outlen), np.uint8)
        z = np.stack([buf & 0xF, buf >> 4], axis=1).reshape(-1) \
            .astype(np.int64)
        if eta == 2:
            z = z[z < 15]
            z = 2 - z % 5
        else:                        # eta == 4
            z = z[z < 9]
            z = 4 - z
        if len(z) >= N:
            return z[:N]
        outlen *= 2


def expand_s(rho_prime: bytes,
             p: ParameterSet) -> Tuple[np.ndarray, np.ndarray]:
    """ExpandS (Alg 33): secret vectors s1 [l, 256], s2 [k, 256]."""
    s1 = np.stack([_rej_bounded_poly(rho_prime + r.to_bytes(2, "little"),
                                     p.eta) for r in range(p.l)])
    s2 = np.stack([_rej_bounded_poly(rho_prime
                                     + (p.l + r).to_bytes(2, "little"),
                                     p.eta) for r in range(p.k)])
    return s1, s2


def expand_mask(rho2: bytes, kappa: int, p: ParameterSet) -> np.ndarray:
    """ExpandMask (Alg 34): the signer's y vector [l, 256], centered."""
    c = p.z_bits
    out = np.empty((p.l, N), np.int64)
    for r in range(p.l):
        v = _shake256(rho2 + (kappa + r).to_bytes(2, "little"), 32 * c)
        out[r] = p.gamma1 - bitunpack(np.frombuffer(v, np.uint8), c, N)
    return out


def sample_in_ball(c_tilde: bytes, p: ParameterSet) -> np.ndarray:
    """SampleInBall (Alg 29): τ ±1 coefficients, centered int64 [256]."""
    outlen = 8 + 8 * p.tau
    while True:
        buf = _shake256(c_tilde, outlen)
        signs = int.from_bytes(buf[:8], "little")
        c = np.zeros(N, np.int64)
        pos = 8
        ok = True
        for i in range(N - p.tau, N):
            while True:
                if pos >= len(buf):
                    ok = False
                    break
                j = buf[pos]
                pos += 1
                if j <= i:
                    break
            if not ok:
                break
            c[i] = c[j]
            c[j] = 1 - 2 * (signs & 1)
            signs >>= 1
        if ok:
            return c
        outlen *= 2


# ---------------------------------------------------------------------------
# rounding (FIPS 204 §7.4) — numpy int64, centered representations
# ---------------------------------------------------------------------------

def power2round(t: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(t1, t0) with t = t1·2^d + t0, t0 centered in (-2^{d-1}, 2^{d-1}]."""
    t = np.asarray(t, np.int64)
    rm = t % (1 << D)
    r0 = np.where(rm > (1 << (D - 1)), rm - (1 << D), rm)
    return (t - r0) >> D, r0


def decompose(r: np.ndarray,
              gamma2: int) -> Tuple[np.ndarray, np.ndarray]:
    """(r1, r0) with r ≡ r1·2γ2 + r0 and the q-1 wrap special case."""
    r = np.asarray(r, np.int64)
    two = 2 * gamma2
    rm = r % two
    r0 = np.where(rm > gamma2, rm - two, rm)
    special = (r - r0) == Q - 1
    r1 = np.where(special, 0, (r - r0) // two)
    r0 = np.where(special, r0 - 1, r0)
    return r1, r0


def make_hint(z: np.ndarray, r: np.ndarray,
              gamma2: int) -> np.ndarray:
    """MakeHint (Alg 39): 1 where adding z changes HighBits(r)."""
    r1, _ = decompose(r, gamma2)
    v1, _ = decompose((r + z) % Q, gamma2)
    return (r1 != v1).astype(np.uint8)


def w1_encode(w1: np.ndarray, p: ParameterSet) -> bytes:
    """w1Encode (Alg 28): SimpleBitPack of the [k, 256] w1 lanes."""
    return bitpack(np.asarray(w1, np.int64).reshape(-1), p.w1_bits) \
        .tobytes()


# ---------------------------------------------------------------------------
# hint encoding (Alg 20/21 — the decode validity rules are part of the
# signature's malleability surface, so HintBitUnpack rejects exactly
# what FIPS 204 rejects: count overflow, unsorted/duplicate indices,
# nonzero padding)
# ---------------------------------------------------------------------------

def hint_bit_pack(h: np.ndarray, p: ParameterSet) -> bytes:
    y = bytearray(p.omega + p.k)
    idx = 0
    for i in range(p.k):
        for j in range(N):
            if h[i, j]:
                y[idx] = j
                idx += 1
        y[p.omega + i] = idx
    return bytes(y)


def hint_bit_unpack(y: bytes, p: ParameterSet) -> Optional[np.ndarray]:
    h = np.zeros((p.k, N), np.uint8)
    idx = 0
    for i in range(p.k):
        end = y[p.omega + i]
        if end < idx or end > p.omega:
            return None
        first = idx
        while idx < end:
            if idx > first and y[idx] <= y[idx - 1]:
                return None
            h[i, y[idx]] = 1
            idx += 1
    for j in range(idx, p.omega):
        if y[j] != 0:
            return None
    return h


# ---------------------------------------------------------------------------
# key / signature encodings
# ---------------------------------------------------------------------------

def pk_encode(rho: bytes, t1: np.ndarray) -> bytes:
    return rho + bitpack(np.asarray(t1, np.int64).reshape(-1),
                         10).tobytes()


def pk_decode(pk: bytes, p: ParameterSet) -> Tuple[bytes, np.ndarray]:
    if len(pk) != p.pk_size:
        raise ValueError(
            f"{p.name} public key must be {p.pk_size} bytes, "
            f"got {len(pk)}")
    rho = pk[:32]
    t1 = bitunpack(np.frombuffer(pk[32:], np.uint8), 10,
                   p.k * N).reshape(p.k, N)
    return rho, t1


def sig_encode(c_tilde: bytes, z: np.ndarray, h: np.ndarray,
               p: ParameterSet) -> bytes:
    zenc = bitpack(p.gamma1 - np.asarray(z, np.int64).reshape(-1),
                   p.z_bits).tobytes()
    return c_tilde + zenc + hint_bit_pack(h, p)


def sig_decode(sig: bytes, p: ParameterSet
               ) -> Optional[Tuple[bytes, np.ndarray, np.ndarray]]:
    """(c̃, z centered [l, 256], h [k, 256]) or None when the hint
    encoding is malformed. The caller checks the total length."""
    c_tilde = sig[: p.lam // 4]
    z_len = p.l * 32 * p.z_bits
    zbuf = np.frombuffer(sig[p.lam // 4: p.lam // 4 + z_len], np.uint8)
    z = p.gamma1 - bitunpack(zbuf, p.z_bits, p.l * N).reshape(p.l, N)
    h = hint_bit_unpack(sig[p.lam // 4 + z_len:], p)
    if h is None:
        return None
    return c_tilde, z, h


# ---------------------------------------------------------------------------
# key objects
# ---------------------------------------------------------------------------

def _matvec_ntt(a_hat: np.ndarray, x_hat: np.ndarray) -> np.ndarray:
    """NTT-domain matrix·vector: [k, l, 256] ∘ [l, 256] → [k, 256]."""
    return ((a_hat * x_hat[None, :, :]) % Q).sum(axis=1) % Q


class MLDSAPublicKey:
    """ML-DSA public key: parameter set + the FIPS 204 pk encoding.

    Duck-typed for the JWK/keyset layer the way ``HostECPublicKey``
    is: ``parameter_set`` routes ``key_matches_alg``, and the heavy
    per-key precompute (Â from ρ, t̂1·2^d, tr) is cached on first use
    so JWKS parsing stays cheap.
    """

    __slots__ = ("parameter_set", "pk", "rho", "t1", "_a_hat",
                 "_t1_hat_2d", "_tr")

    def __init__(self, parameter_set: str, pk: bytes):
        if parameter_set not in PARAMS:
            raise ValueError(
                f"unknown ML-DSA parameter set {parameter_set!r}")
        p = PARAMS[parameter_set]
        self.parameter_set = parameter_set
        self.pk = bytes(pk)
        self.rho, self.t1 = pk_decode(self.pk, p)
        self._a_hat: Optional[np.ndarray] = None
        self._t1_hat_2d: Optional[np.ndarray] = None
        self._tr: Optional[bytes] = None

    @property
    def params(self) -> ParameterSet:
        return PARAMS[self.parameter_set]

    @property
    def tr(self) -> bytes:
        if self._tr is None:
            self._tr = _shake256(self.pk, 64)
        return self._tr

    @property
    def a_hat(self) -> np.ndarray:
        if self._a_hat is None:
            self._a_hat = expand_a(self.rho, self.params)
        return self._a_hat

    @property
    def t1_hat_2d(self) -> np.ndarray:
        if self._t1_hat_2d is None:
            self._t1_hat_2d = _ntt.ntt_ref((self.t1 << D) % Q)
        return self._t1_hat_2d

    def verify(self, signature: bytes, message: bytes) -> bool:
        return py_verify(self, signature, message)


class MLDSAPrivateKey:
    """Fixture-only deterministic signer (FIPS 204 Alg 7, rnd = 0³²).

    Exists so KAT vectors, bench tokens, and the hybrid-migration
    chaos fixtures can be generated dependency-free and byte-stably —
    nothing here is constant-time or production signing.
    """

    __slots__ = ("public_key", "_K", "_s1_hat", "_s2_hat", "_t0_hat")

    def __init__(self, pub: MLDSAPublicKey, K: bytes, s1: np.ndarray,
                 s2: np.ndarray, t0: np.ndarray):
        self.public_key = pub
        self._K = K
        self._s1_hat = _ntt.ntt_ref(s1 % Q)
        self._s2_hat = _ntt.ntt_ref(s2 % Q)
        self._t0_hat = _ntt.ntt_ref(t0 % Q)

    def sign(self, message: bytes, ctx: bytes = b"") -> bytes:
        if len(ctx) > 255:
            raise ValueError("ctx must be at most 255 bytes")
        m_prime = b"\x00" + bytes([len(ctx)]) + ctx + message
        return self._sign_internal(m_prime, b"\x00" * 32)

    def _sign_internal(self, m_prime: bytes, rnd: bytes) -> bytes:
        pub = self.public_key
        p = pub.params
        center = _center
        mu = _shake256(pub.tr + m_prime, 64)
        rho2 = _shake256(self._K + rnd + mu, 64)
        kappa = 0
        while True:
            y = expand_mask(rho2, kappa, p)
            kappa += p.l
            w = _ntt.intt_ref(_matvec_ntt(pub.a_hat,
                                          _ntt.ntt_ref(y % Q)))
            w1, _ = decompose(w, p.gamma2)
            c_tilde = _shake256(mu + w1_encode(w1, p), p.lam // 4)
            c_hat = _ntt.ntt_ref(sample_in_ball(c_tilde, p) % Q)
            cs1 = center(_ntt.intt_ref((c_hat * self._s1_hat) % Q))
            z = y + cs1
            if np.abs(z).max() >= p.gamma1 - p.beta:
                continue
            cs2 = center(_ntt.intt_ref((c_hat * self._s2_hat) % Q))
            _, r0 = decompose((w - cs2) % Q, p.gamma2)
            if np.abs(r0).max() >= p.gamma2 - p.beta:
                continue
            ct0 = center(_ntt.intt_ref((c_hat * self._t0_hat) % Q))
            if np.abs(ct0).max() >= p.gamma2:
                continue
            h = make_hint(-ct0 % Q, (w - cs2 + ct0) % Q, p.gamma2)
            if int(h.sum()) > p.omega:
                continue
            return sig_encode(c_tilde, z, h, p)


def _center(x: np.ndarray) -> np.ndarray:
    """Representative in (-(q-1)/2, (q-1)/2]."""
    x = np.asarray(x, np.int64) % Q
    return np.where(x > (Q - 1) // 2, x - Q, x)


def keygen(parameter_set: str,
           seed: bytes) -> Tuple[MLDSAPrivateKey, MLDSAPublicKey]:
    """ML-DSA.KeyGen_internal (Alg 6) from a 32-byte seed ξ."""
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    p = PARAMS[parameter_set]
    hh = _shake256(seed + bytes([p.k, p.l]), 128)
    rho, rho_prime, K = hh[:32], hh[32:96], hh[96:128]
    a_hat = expand_a(rho, p)
    s1, s2 = expand_s(rho_prime, p)
    t = (_ntt.intt_ref(_matvec_ntt(a_hat, _ntt.ntt_ref(s1 % Q)))
         + s2) % Q
    t1, t0 = power2round(t)
    pub = MLDSAPublicKey(parameter_set, pk_encode(rho, t1))
    pub._a_hat = a_hat               # already expanded — share it
    return MLDSAPrivateKey(pub, K, s1, s2, t0), pub


# ---------------------------------------------------------------------------
# pure-integer host oracle (the ec._py_verify_one analog)
# ---------------------------------------------------------------------------

def _decode_checked(sig: bytes, p: ParameterSet):
    """Length + hint-validity + z-range gates shared by oracle and
    engine prep. Returns (c̃, z centered, h) or None (reject)."""
    if len(sig) != p.sig_size:
        return None
    dec = sig_decode(sig, p)
    if dec is None:
        return None
    c_tilde, z, h = dec
    if int(np.abs(z).max()) >= p.gamma1 - p.beta:
        return None
    return c_tilde, z, h


def mu_for(tr: bytes, message: bytes, ctx: bytes = b"") -> bytes:
    """μ = SHAKE256(tr ‖ M', 64) with the pure-ML-DSA domain prefix."""
    return _shake256(tr + b"\x00" + bytes([len(ctx)]) + ctx + message,
                     64)


def py_verify(pub: MLDSAPublicKey, signature: bytes,
              message: bytes) -> bool:
    """ML-DSA.Verify (Alg 8), entirely host-side exact integers.

    The oracle of last resort AND the parity reference: the device
    engine must reproduce these verdicts bit-for-bit, malformed and
    adversarial inputs included.
    """
    p = pub.params
    dec = _decode_checked(bytes(signature), p)
    if dec is None:
        return False
    c_tilde, z, h = dec
    mu = mu_for(pub.tr, bytes(message))
    c_hat = _ntt.ntt_ref(sample_in_ball(c_tilde, p) % Q)
    z_hat = _ntt.ntt_ref(z % Q)
    w_approx = _ntt.intt_ref(
        (_matvec_ntt(pub.a_hat, z_hat)
         - (c_hat * pub.t1_hat_2d) % Q) % Q)
    w1 = _ntt.use_hint_ref(h, w_approx, p.gamma2)
    return _shake256(mu + w1_encode(w1, p), p.lam // 4) == c_tilde


# ---------------------------------------------------------------------------
# device engine: per-parameter-set key tables + batched verify
# ---------------------------------------------------------------------------

class MLDSAKeyTable:
    """Device-resident ML-DSA key material for ONE parameter set.

    Per key: Â (k·l NTT-domain polys, expanded host-side from ρ once)
    and t̂1·2^d, both uploaded in Montgomery form so every pointwise
    device multiply against per-token plain-domain data is a single
    ``mont_mul`` — the key-gather axis, same shape as the RSA/EC
    tables.
    """

    def __init__(self, parameter_set: str, keys: Sequence[MLDSAPublicKey]):
        import jax.numpy as jnp

        p = PARAMS[parameter_set]
        self.params = p
        self.parameter_set = parameter_set
        self.keys = list(keys)
        a = np.stack([k.a_hat for k in self.keys])         # [nk,k,l,256]
        t = np.stack([k.t1_hat_2d for k in self.keys])     # [nk,k,256]
        self.a_mont = jnp.asarray(
            ((a << _ntt.MONT_BITS) % Q).astype(np.uint32))
        self.t1_mont = jnp.asarray(
            ((t << _ntt.MONT_BITS) % Q).astype(np.uint32))


def _w1_core(a_mont, t1_mont, z, c, h, key_idx, gamma2: int):
    """The jitted device graph: w1 lanes from per-token z/c/h lanes.

    z: [B, l, 256] uint32 plain-domain canonical; c: [B, 256];
    h: [B, k, 256] uint8; key_idx: [B] int32. Returns [B, k, 256]
    uint8 w1 values in [0, m).
    """
    import jax.numpy as jnp

    z_hat = _ntt.ntt(z)                         # [B, l, 256]
    c_hat = _ntt.ntt(c)                         # [B, 256]
    a = a_mont[key_idx]                         # [B, k, l, 256]
    t1 = t1_mont[key_idx]                       # [B, k, 256]
    prod = _ntt.mont_mul(a, z_hat[:, None, :, :])
    # Each term < q < 2^23 and l ≤ 7, so the plain uint32 sum cannot
    # overflow before the fold back into [0, q).
    acc = jnp.sum(prod, axis=2, dtype=jnp.uint32) % np.uint32(Q)
    acc = _ntt.sub_q(acc, _ntt.mont_mul(c_hat[:, None, :], t1))
    w = _ntt.intt(acc)
    return _ntt.use_hint(h, w, gamma2).astype(jnp.uint8)


_CORE_JIT = None


def _core_jit():
    global _CORE_JIT
    if _CORE_JIT is None:
        import jax

        _CORE_JIT = jax.jit(_w1_core, static_argnums=(6,))
    return _CORE_JIT


def verify_mldsa_core_pending(table: MLDSAKeyTable, z: np.ndarray,
                              c: np.ndarray, h: np.ndarray,
                              key_idx: np.ndarray, mesh=None):
    """Queue the device w1 computation; returns the (async) device
    array [B, k, 256] uint8. All H2D transfers are dispatched before
    this returns — nothing blocks until the caller materializes."""
    import jax

    if mesh is not None:
        from ..parallel.place import shard_batch

        z = shard_batch(mesh, z)
        c = shard_batch(mesh, c)
        h = shard_batch(mesh, h)
        key_idx = shard_batch(mesh, key_idx)
    else:
        z = jax.device_put(z)
        c = jax.device_put(c)
        h = jax.device_put(h)
        key_idx = jax.device_put(key_idx)
    return _core_jit()(table.a_mont, table.t1_mont, z, c, h, key_idx,
                       table.params.gamma2)


def w1_resident(table: MLDSAKeyTable, z, c, h, key_idx):
    """Dispatch the w1 core on ALREADY-RESIDENT device arrays — the
    engine-benchmark entry point (no H2D on the timed path)."""
    return _core_jit()(table.a_mont, table.t1_mont, z, c, h, key_idx,
                       table.params.gamma2)


class _PreppedChunk:
    """Host-side decode of one ML-DSA chunk, ready for dispatch."""

    __slots__ = ("z", "c", "h", "key_idx", "valid", "mus", "cts", "m")

    def __init__(self, table: MLDSAKeyTable, sigs: Sequence[bytes],
                 msgs: Sequence[bytes], key_idx: np.ndarray, pad: int):
        p = table.params
        m = len(sigs)
        self.m = m
        self.z = np.zeros((pad, p.l, N), np.uint32)
        self.c = np.zeros((pad, N), np.uint32)
        self.h = np.zeros((pad, p.k, N), np.uint8)
        self.key_idx = np.zeros(pad, np.int32)
        self.key_idx[:m] = np.asarray(key_idx, np.int32)[:m]
        self.valid = np.zeros(pad, bool)
        self.mus: List[Optional[bytes]] = [None] * pad
        self.cts: List[Optional[bytes]] = [None] * pad
        for i in range(m):
            dec = _decode_checked(bytes(sigs[i]), p)
            if dec is None:
                continue
            c_tilde, zi, hi = dec
            key = table.keys[int(self.key_idx[i])]
            self.z[i] = (zi % Q).astype(np.uint32)
            self.c[i] = (sample_in_ball(c_tilde, p) % Q).astype(np.uint32)
            self.h[i] = hi
            self.valid[i] = True
            self.mus[i] = mu_for(key.tr, bytes(msgs[i]))
            self.cts[i] = c_tilde

    def finalize(self, table: MLDSAKeyTable,
                 w1: np.ndarray) -> np.ndarray:
        """Host finish: w1Encode + the μ/c̃ SHAKE compare → [pad] bool."""
        p = table.params
        ok = np.zeros(len(self.valid), bool)
        for i in np.nonzero(self.valid)[0]:
            enc = w1_encode(w1[i], p)
            ok[i] = _shake256(self.mus[i] + enc,
                              p.lam // 4) == self.cts[i]
        return ok


def verify_mldsa_pending(table: MLDSAKeyTable, sigs: Sequence[bytes],
                         msgs: Sequence[bytes], key_idx: np.ndarray,
                         pad: Optional[int] = None, mesh=None):
    """Two-phase batched verify: host decode + device dispatch NOW,
    returns ``fin()`` → [pad] bool verdicts (materializes on call).

    Invalid-at-decode tokens (wrong length, malformed hints,
    out-of-range z) never touch the device and finish False — the
    exact verdicts ``py_verify`` produces.
    """
    if pad is None:
        pad = len(sigs)
    prep = _PreppedChunk(table, sigs, msgs, key_idx, pad)
    if prep.valid.any():
        w1_dev = verify_mldsa_core_pending(
            table, prep.z, prep.c, prep.h, prep.key_idx, mesh=mesh)
    else:
        w1_dev = None

    def fin() -> np.ndarray:
        w1 = (np.asarray(w1_dev) if w1_dev is not None
              else np.zeros((pad, table.params.k, N), np.uint8))
        return prep.finalize(table, w1)

    return fin


def verify_mldsa_batch(table: MLDSAKeyTable, sigs: Sequence[bytes],
                       msgs: Sequence[bytes],
                       key_idx: np.ndarray, mesh=None) -> np.ndarray:
    """[N] bool verdicts for one ML-DSA bucket (blocking interface)."""
    return verify_mldsa_pending(table, sigs, msgs, key_idx,
                                mesh=mesh)()


# ---------------------------------------------------------------------------
# FUSED single-round-trip verify: μ, SampleInBall, the NTT network,
# w1Encode, and the final c̃ compare ALL on-device (batched Keccak via
# pallas_keccak) — the host decodes bytes and never hashes per token.
# ---------------------------------------------------------------------------

# SampleInBall squeeze budget: 3 SHAKE256 blocks = 408 bytes. The
# oracle's grow-and-retry loop needs ~8+1.1·τ bytes in expectation
# (≤ 76 even for τ=60), so overflow probability is astronomically
# small — but parity is structural, not probabilistic: a token whose
# sampling walks past the budget raises an ``exhausted`` flag and
# re-verifies on the pure-int host oracle (the EC degeneracy-probe
# contract).
_SIB_BLOCKS = 3
_SIB_BYTES = _SIB_BLOCKS * 136


def fused_enabled() -> bool:
    """Fused device verify: CAP_TPU_MLDSA_FUSED=1/0 (default ON).

    ON makes a packed ML-DSA batch a SINGLE host round-trip: one
    dispatch, one materializing sync, zero per-token host SHAKE.
    OFF restores the r11 two-phase path (host μ/c̃ hashing around the
    device NTT) — kept as the A/B arm and the conservative fallback.
    """
    return os.environ.get("CAP_TPU_MLDSA_FUSED", "1") \
        not in ("0", "false", "no")


def _w1_pad_lanes(p: ParameterSet) -> Tuple[int, np.ndarray]:
    """(n_blocks, XOR pad tensor [n_blocks, 25, 2]) for the fixed-
    length SHAKE256(μ ‖ w1enc) absorb of one parameter set."""
    from . import pallas_keccak as _kk

    total = 64 + N * p.k * p.w1_bits // 8
    nb = total // 136 + 1                 # pad10*1 always adds a byte
    buf = np.zeros(nb * 136, np.uint8)
    buf[total] = _kk.DOMAIN_SHAKE
    buf[nb * 136 - 1] ^= 0x80
    lanes = _kk.interleave(buf.view("<u8")).reshape(nb, 17, 2)
    out = np.zeros((nb, 25, 2), np.uint32)
    out[:, :17] = lanes
    return nb, out


_W1_PAD: Dict[str, Tuple[int, np.ndarray]] = {}


def _fused_core(a_mont, t1_mont, mu_blocks, mu_nblk, ct_block,
                ct_cmp, z, h, key_idx, valid, w1_pad,
                gamma2: int, tau: int, w1_bits: int):
    """The one-dispatch device graph: [B] accept bits + exhausted
    flags from decoded byte lanes. Everything between the H2D of the
    prepped lanes and the D2H of two bit vectors happens here."""
    import jax.numpy as jnp

    from . import pallas_keccak as _kk

    b = z.shape[0]
    # μ = SHAKE256(tr ‖ 0x00 ‖ 0x00 ‖ M, 64): masked variable-length
    # absorb; the first 8 lanes of the final state are μ's 64 bytes.
    mu_state = _kk.absorb(mu_blocks, mu_nblk)
    mu_lanes = mu_state[:, :8, :]                        # [B, 8, 2]

    # SampleInBall: SHAKE256(c̃) squeezed to the fixed budget, then
    # the Fisher-Yates walk as a τ-step scan (j-draws via first-
    # acceptable-byte argmax, exactly the oracle's trajectory).
    sib_state = _kk.absorb_fixed(ct_block)
    sib_bytes = _kk.lanes_to_bytes(
        _kk.squeeze_lanes(sib_state, 136, _SIB_BLOCKS)) \
        .astype(jnp.int32)                               # [B, 408]
    lane0 = sib_state[:, 0, :]                           # signs u64
    sh = np.arange(32, dtype=np.uint32)
    sign_bits = jnp.stack(
        [(lane0[:, 0, None] >> sh) & np.uint32(1),
         (lane0[:, 1, None] >> sh) & np.uint32(1)],
        axis=-1).reshape(b, 64)                          # bit t of u64
    idx408 = np.arange(_SIB_BYTES, dtype=np.int32)
    coeff_idx = np.arange(N, dtype=np.int32)

    import jax

    def sib_step(carry, it):
        c, pos, exhausted = carry
        i, t = it
        ok_pos = (idx408[None, :] >= pos[:, None]) & (sib_bytes <= i)
        found = ok_pos.any(axis=1)
        p_sel = jnp.argmax(ok_pos, axis=1).astype(jnp.int32)
        j = jnp.take_along_axis(sib_bytes, p_sel[:, None],
                                axis=1)[:, 0]            # byte value
        sign = jnp.take_along_axis(sign_bits, jnp.full((b, 1), t),
                                   axis=1)[:, 0]
        cj = jnp.take_along_axis(c, j[:, None].astype(jnp.int32),
                                 axis=1)[:, 0]
        c = jnp.where(coeff_idx[None, :] == i, cj[:, None], c)
        pm1 = jnp.where(sign != 0, jnp.uint32(Q - 1), jnp.uint32(1))
        c = jnp.where(coeff_idx[None, :] == j[:, None].astype(jnp.int32),
                      pm1[:, None], c)
        pos = jnp.where(found, p_sel + 1, pos)
        return (c, pos, exhausted | ~found), None

    i_vals = jnp.arange(N - tau, N, dtype=jnp.int32)
    t_vals = jnp.arange(tau, dtype=jnp.int32)
    c0 = jnp.zeros((b, N), jnp.uint32)
    pos0 = jnp.full(b, 8, jnp.int32)
    (c, _pos, exhausted), _ = jax.lax.scan(
        sib_step, (c0, pos0, jnp.zeros(b, bool)), (i_vals, t_vals))

    # the r11 NTT network, unchanged (pallas-fused when enabled)
    z_hat = _ntt.ntt(z)
    c_hat = _ntt.ntt(c)
    a = a_mont[key_idx]
    t1 = t1_mont[key_idx]
    prod = _ntt.mont_mul(a, z_hat[:, None, :, :])
    acc = jnp.sum(prod, axis=2, dtype=jnp.uint32) % np.uint32(Q)
    acc = _ntt.sub_q(acc, _ntt.mont_mul(c_hat[:, None, :], t1))
    w = _ntt.intt(acc)
    w1 = _ntt.use_hint(h, w, gamma2)                     # [B, k, 256]

    # w1Encode on-device: LSB-first bits -> interleaved lanes directly
    bit_sh = np.arange(w1_bits, dtype=np.uint32)
    bits = ((w1[..., None] >> bit_sh) & np.uint32(1)).reshape(b, -1)
    w1_lanes = _kk.bits_to_lanes(bits)                   # [B, nw, 2]

    # SHAKE256(μ ‖ w1enc, λ/4) ?= c̃ — fixed-shape absorb; the pad
    # rides a precomputed XOR tensor.
    nb2 = w1_pad.shape[0]
    content = jnp.concatenate(
        [mu_lanes, w1_lanes,
         jnp.zeros((b, nb2 * 17 - 8 - w1_lanes.shape[1], 2),
                   jnp.uint32)], axis=1).reshape(b, nb2, 17, 2)
    blocks2 = jnp.zeros((b, nb2, 25, 2), jnp.uint32)
    blocks2 = blocks2.at[:, :, :17].set(content) ^ w1_pad[None]
    st2 = _kk.absorb_fixed(blocks2)
    nc = ct_cmp.shape[1]
    match = (st2[:, :nc, :] == ct_cmp).all(axis=(1, 2))
    return match & valid & ~exhausted, exhausted & valid


_FUSED_JIT = None


def _fused_jit():
    global _FUSED_JIT
    if _FUSED_JIT is None:
        import jax

        _FUSED_JIT = jax.jit(_fused_core,
                             static_argnums=(11, 12, 13))
    return _FUSED_JIT


class _FusedPrep:
    """Host-side decode of one chunk for the fused path: byte
    shuffling ONLY — signature gates, z/hint unpack, μ-input block
    packing, c̃ lane conversion. No hashlib anywhere."""

    __slots__ = ("z", "h", "key_idx", "valid", "mu_blocks", "mu_nblk",
                 "ct_block", "ct_cmp", "m", "sigs", "msgs")

    def __init__(self, table: MLDSAKeyTable, sigs: Sequence[bytes],
                 msgs: Sequence[bytes], key_idx: np.ndarray, pad: int):
        from . import pallas_keccak as _kk

        p = table.params
        m = len(sigs)
        self.m = m
        self.sigs = [bytes(s) for s in sigs]
        self.msgs = [bytes(x) for x in msgs]
        self.z = np.zeros((pad, p.l, N), np.uint32)
        self.h = np.zeros((pad, p.k, N), np.uint8)
        self.key_idx = np.zeros(pad, np.int32)
        self.key_idx[:m] = np.asarray(key_idx, np.int32)[:m]
        self.valid = np.zeros(pad, bool)
        mu_msgs: List[bytes] = [b""] * pad
        ct = np.zeros((pad, p.lam // 4), np.uint8)
        for i in range(m):
            dec = _decode_checked(self.sigs[i], p)
            if dec is None:
                continue
            c_tilde, zi, hi = dec
            key = table.keys[int(self.key_idx[i])]
            self.z[i] = (zi % Q).astype(np.uint32)
            self.h[i] = hi
            self.valid[i] = True
            mu_msgs[i] = key.tr + b"\x00\x00" + self.msgs[i]
            ct[i] = np.frombuffer(c_tilde, np.uint8)
        # bucket the μ block count to a power of two so message-length
        # jitter cannot fan out into per-batch recompiles
        blocks, nblk = _kk.pack_blocks(mu_msgs, 136)
        nb = 4
        while nb < blocks.shape[1]:
            nb *= 2
        if blocks.shape[1] < nb:
            blocks = np.concatenate(
                [blocks, np.zeros((pad, nb - blocks.shape[1], 25, 2),
                                  np.uint32)], axis=1)
        self.mu_blocks = blocks
        self.mu_nblk = nblk
        # c̃: one absorb block + whole-lane compare target
        ctb = np.zeros((pad, 1, 25, 2), np.uint32)
        pad_buf = np.zeros((pad, 136), np.uint8)
        pad_buf[:, : p.lam // 4] = ct
        pad_buf[:, p.lam // 4] = _kk.DOMAIN_SHAKE
        pad_buf[:, 135] ^= 0x80
        ctb[:, 0, :17] = _kk.interleave(
            pad_buf.view("<u8").reshape(pad, 17))
        self.ct_block = ctb
        self.ct_cmp = _kk.interleave(
            ct.view("<u8").reshape(pad, p.lam // 32))


def verify_mldsa_fused_pending(table: MLDSAKeyTable,
                               sigs: Sequence[bytes],
                               msgs: Sequence[bytes],
                               key_idx: np.ndarray,
                               pad: Optional[int] = None, mesh=None):
    """Single-round-trip batched verify: decode + ONE device dispatch
    now; the returned ``fin()`` materializes [pad] bool verdicts.

    Invalid-at-decode tokens finish False without touching the
    device-side hash chain; budget-exhausted SampleInBall tokens
    (probability ≈ 0, flagged on-device) re-verify on the pure-int
    oracle so verdict parity with ``py_verify`` stays structural.
    """
    from .. import telemetry

    if pad is None:
        pad = len(sigs)
    p = table.params
    prep = _FusedPrep(table, sigs, msgs, key_idx, pad)
    pair = _W1_PAD.get(table.parameter_set)
    if pair is None:
        pair = _W1_PAD[table.parameter_set] = _w1_pad_lanes(p)
    _nb2, w1_pad = pair
    if prep.valid.any():
        import jax

        arrs = [prep.mu_blocks, prep.mu_nblk, prep.ct_block,
                prep.ct_cmp, prep.z, prep.h, prep.key_idx, prep.valid,
                w1_pad]
        if mesh is not None:
            from ..parallel.place import shard_batch

            put = [shard_batch(mesh, a) for a in arrs[:-1]]
            put.append(jax.device_put(arrs[-1]))
        else:
            put = [jax.device_put(a) for a in arrs]
        out = _fused_jit()(table.a_mont, table.t1_mont, *put,
                           p.gamma2, p.tau, p.w1_bits)
    else:
        out = None

    def fin() -> np.ndarray:
        if out is None:
            return np.zeros(pad, bool)
        ok = np.asarray(out[0])
        exhausted = np.asarray(out[1])
        if exhausted.any():
            telemetry.count("mldsa.fused.exhausted",
                            int(exhausted.sum()))
            key = table.keys
            for i in np.nonzero(exhausted)[0]:
                if i < prep.m:
                    ok[i] = py_verify(key[int(prep.key_idx[i])],
                                      prep.sigs[i], prep.msgs[i])
        return ok

    return fin


def host_w1(table: MLDSAKeyTable, prep: "_PreppedChunk") -> np.ndarray:
    """numpy mirror of the device w1 graph over a prepped chunk — the
    parity reference for tests and the resident bench's expected
    lanes."""
    p = table.params
    out = np.zeros((len(prep.valid), p.k, N), np.int64)
    for i in np.nonzero(prep.valid)[0]:
        key = table.keys[int(prep.key_idx[i])]
        z_hat = _ntt.ntt_ref(prep.z[i].astype(np.int64))
        c_hat = _ntt.ntt_ref(prep.c[i].astype(np.int64))
        w = _ntt.intt_ref(
            (_matvec_ntt(key.a_hat, z_hat)
             - (c_hat * key.t1_hat_2d) % Q) % Q)
        out[i] = _ntt.use_hint_ref(prep.h[i], w, p.gamma2)
    return out
