"""Batched SHA-512/384 as JAX programs (FIPS 180-4, from the spec).

Companion to tpu/sha256.py for the PS384/PS512 device PSS tails (and
the Ed25519 k-hash later): 64-bit words emulated as (hi, lo) uint32
pairs — TPUs have no native u64 — with explicit carry propagation on
adds and pairwise rotates. Same lax.scan structure as SHA-256 (an
unrolled 80-round compression would be ~10k XLA ops per call site).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

_K512 = [
    0x428a2f98d728ae22, 0x7137449123ef65cd, 0xb5c0fbcfec4d3b2f,
    0xe9b5dba58189dbbc, 0x3956c25bf348b538, 0x59f111f1b605d019,
    0x923f82a4af194f9b, 0xab1c5ed5da6d8118, 0xd807aa98a3030242,
    0x12835b0145706fbe, 0x243185be4ee4b28c, 0x550c7dc3d5ffb4e2,
    0x72be5d74f27b896f, 0x80deb1fe3b1696b1, 0x9bdc06a725c71235,
    0xc19bf174cf692694, 0xe49b69c19ef14ad2, 0xefbe4786384f25e3,
    0x0fc19dc68b8cd5b5, 0x240ca1cc77ac9c65, 0x2de92c6f592b0275,
    0x4a7484aa6ea6e483, 0x5cb0a9dcbd41fbd4, 0x76f988da831153b5,
    0x983e5152ee66dfab, 0xa831c66d2db43210, 0xb00327c898fb213f,
    0xbf597fc7beef0ee4, 0xc6e00bf33da88fc2, 0xd5a79147930aa725,
    0x06ca6351e003826f, 0x142929670a0e6e70, 0x27b70a8546d22ffc,
    0x2e1b21385c26c926, 0x4d2c6dfc5ac42aed, 0x53380d139d95b3df,
    0x650a73548baf63de, 0x766a0abb3c77b2a8, 0x81c2c92e47edaee6,
    0x92722c851482353b, 0xa2bfe8a14cf10364, 0xa81a664bbc423001,
    0xc24b8b70d0f89791, 0xc76c51a30654be30, 0xd192e819d6ef5218,
    0xd69906245565a910, 0xf40e35855771202a, 0x106aa07032bbd1b8,
    0x19a4c116b8d2d0c8, 0x1e376c085141ab53, 0x2748774cdf8eeb99,
    0x34b0bcb5e19b48a8, 0x391c0cb3c5c95a63, 0x4ed8aa4ae3418acb,
    0x5b9cca4f7763e373, 0x682e6ff3d6b2b8a3, 0x748f82ee5defb2fc,
    0x78a5636f43172f60, 0x84c87814a1f0ab72, 0x8cc702081a6439ec,
    0x90befffa23631e28, 0xa4506cebde82bde9, 0xbef9a3f7b2c67915,
    0xc67178f2e372532b, 0xca273eceea26619c, 0xd186b8c721c0c207,
    0xeada7dd6cde0eb1e, 0xf57d4f7fee6ed178, 0x06f067aa72176fba,
    0x0a637dc5a2c898a6, 0x113f9804bef90dae, 0x1b710b35131c471b,
    0x28db77f523047d84, 0x32caab7b40c72493, 0x3c9ebe0a15c9bebc,
    0x431d67c49c100d4c, 0x4cc5d4becb3e42b6, 0x597f299cfc657e2a,
    0x5fcb6fab3ad6faec, 0x6c44198c4a475817,
]

_H512 = [0x6a09e667f3bcc908, 0xbb67ae8584caa73b, 0x3c6ef372fe94f82b,
         0xa54ff53a5f1d36f1, 0x510e527fade682d1, 0x9b05688c2b3e6c1f,
         0x1f83d9abfb41bd6b, 0x5be0cd19137e2179]

_H384 = [0xcbbb9d5dc1059ed8, 0x629a292a367cd507, 0x9159015a3070dd17,
         0x152fecd8f70e5939, 0x67332667ffc00b31, 0x8eb44a8768581511,
         0xdb0c2e0d64f98fa7, 0x47b5481dbefa4fa4]

U32 = jnp.uint32


def _add2(a, b):
    """(hi, lo) + (hi, lo) with carry (mod 2^64)."""
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(U32)
    return (a[0] + b[0] + carry, lo)


def _ror2(x, r: int):
    """64-bit rotate right of a (hi, lo) pair by r ∈ (0, 64)."""
    hi, lo = x
    if r == 32:
        return (lo, hi)
    if r > 32:
        hi, lo = lo, hi
        r -= 32
    # rotate the 64-bit value right by r < 32
    return ((hi >> r) | (lo << (32 - r)),
            (lo >> r) | (hi << (32 - r)))


def _shr2(x, r: int):
    """64-bit logical shift right by r < 32."""
    hi, lo = x
    return (hi >> r, (lo >> r) | (hi << (32 - r)))


def _xor2(*xs):
    hi = xs[0][0]
    lo = xs[0][1]
    for x in xs[1:]:
        hi = hi ^ x[0]
        lo = lo ^ x[1]
    return (hi, lo)


def _round512(st, w_t, kt64):
    a, b, c, d, e, f, g, h = st
    s1 = _xor2(_ror2(e, 14), _ror2(e, 18), _ror2(e, 41))
    ch = ((e[0] & f[0]) ^ (~e[0] & g[0]),
          (e[1] & f[1]) ^ (~e[1] & g[1]))
    t1 = _add2(_add2(_add2(h, s1), _add2(ch, kt64)), w_t)
    s0 = _xor2(_ror2(a, 28), _ror2(a, 34), _ror2(a, 39))
    maj = ((a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
           (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]))
    t2 = _add2(s0, maj)
    return (_add2(t1, t2), a, b, c, _add2(d, t1), e, f, g)


def _compress512_unrolled(state, words):
    """compress512() with the 80 rounds as one fused op chain
    (opt-in experiment — see sha256._unrolled)."""
    w = [(words[2 * i], words[2 * i + 1]) for i in range(16)]
    s = tuple(state)
    for t in range(80):
        if t >= 16:
            w1 = w[t - 15]
            w14 = w[t - 2]
            sg0 = _xor2(_ror2(w1, 1), _ror2(w1, 8), _shr2(w1, 7))
            sg1 = _xor2(_ror2(w14, 19), _ror2(w14, 61), _shr2(w14, 6))
            w.append(_add2(_add2(w[t - 16], sg0), _add2(w[t - 7], sg1)))
        kt = _K512[t]
        s = _round512(s, w[t], (jnp.uint32(kt >> 32),
                                jnp.uint32(kt & 0xFFFFFFFF)))
    return tuple(_add2(a, b) for a, b in zip(state, s))


def compress512(state, words):
    """One SHA-512 compression over the batch.

    state: tuple of 8 (hi, lo) pairs of [N] uint32; words: [32, N]
    uint32 — the 16 message words as interleaved (hi, lo) rows
    (row 2t = hi of word t, row 2t+1 = lo). The scan is the default
    everywhere; CAP_TPU_SHA_UNROLL=1 opts into unrolled rounds (see
    sha256._unrolled).
    """
    from .sha256 import _unrolled

    if _unrolled():
        return _compress512_unrolled(state, words)

    k_hi = jnp.asarray([k >> 32 for k in _K512], np.uint32)
    k_lo = jnp.asarray([k & 0xFFFFFFFF for k in _K512], np.uint32)
    k_arr = jnp.stack([k_hi, k_lo], axis=1)       # [80, 2]

    def round_body(carry, kt):
        st, w_win = carry                          # w_win [32, N]
        a, b, c, d, e, f, g, h = st
        w_t = (w_win[0], w_win[1])
        s1 = _xor2(_ror2(e, 14), _ror2(e, 18), _ror2(e, 41))
        ch = (( e[0] & f[0]) ^ (~e[0] & g[0]),
              ( e[1] & f[1]) ^ (~e[1] & g[1]))
        kt64 = (kt[0], kt[1])
        t1 = _add2(_add2(_add2(h, s1), _add2(ch, kt64)), w_t)
        s0 = _xor2(_ror2(a, 28), _ror2(a, 34), _ror2(a, 39))
        maj = ((a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
               (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]))
        t2 = _add2(s0, maj)
        new_st = (_add2(t1, t2), a, b, c, _add2(d, t1), e, f, g)
        # schedule: W[t+16] = W[t] + σ0(W[t+1]) + W[t+9] + σ1(W[t+14])
        w1 = (w_win[2], w_win[3])
        w9 = (w_win[18], w_win[19])
        w14 = (w_win[28], w_win[29])
        sg0 = _xor2(_ror2(w1, 1), _ror2(w1, 8), _shr2(w1, 7))
        sg1 = _xor2(_ror2(w14, 19), _ror2(w14, 61), _shr2(w14, 6))
        w_new = _add2(_add2(w_t, sg0), _add2(w9, sg1))
        w_win = jnp.concatenate(
            [w_win[2:], w_new[0][None], w_new[1][None]], axis=0)
        return (new_st, w_win), None

    (out, _), _ = lax.scan(round_body, (tuple(state), words),
                           k_arr)
    return tuple(_add2(s, v) for s, v in zip(state, out))


def _bytes_to_words512(block):
    """[N, 128] uint8 → [32, N] uint32 interleaved (hi, lo) pairs."""
    b = block.astype(U32).reshape(block.shape[0], 16, 8)
    hi = (b[:, :, 0] << 24) | (b[:, :, 1] << 16) | \
        (b[:, :, 2] << 8) | b[:, :, 3]
    lo = (b[:, :, 4] << 24) | (b[:, :, 5] << 16) | \
        (b[:, :, 6] << 8) | b[:, :, 7]
    return jnp.stack([hi, lo], axis=2).reshape(
        block.shape[0], 32).T


def _init_state512(n, h0):
    return tuple(
        (jnp.full((n,), int(v >> 32), U32),
         jnp.full((n,), int(v & 0xFFFFFFFF), U32)) for v in h0)


def _digest_bytes512(state, out_words: int):
    cols = []
    for hi, lo in state[:out_words]:
        for word in (hi, lo):
            cols.append((word >> 24).astype(jnp.uint8))
            cols.append(((word >> 16) & 0xFF).astype(jnp.uint8))
            cols.append(((word >> 8) & 0xFF).astype(jnp.uint8))
            cols.append((word & 0xFF).astype(jnp.uint8))
    return jnp.stack(cols, axis=1)


def _hash_fixed(msgs, h0, out_words: int):
    n, length = msgs.shape
    assert length <= 111, "single-block limit (SHA-512 family)"
    block = jnp.zeros((n, 128), jnp.uint8)
    block = block.at[:, :length].set(msgs)
    block = block.at[:, length].set(jnp.uint8(0x80))
    bits = length * 8
    block = block.at[:, 126].set(jnp.uint8(bits >> 8))
    block = block.at[:, 127].set(jnp.uint8(bits & 0xFF))
    state = compress512(_init_state512(n, h0), _bytes_to_words512(block))
    return _digest_bytes512(state, out_words)


def _hash_var(msgs, lens, max_len: int, h0, out_words: int):
    n = msgs.shape[0]
    n_blocks = (max_len + 17 + 127) // 128
    buf = jnp.zeros((n, n_blocks * 128), jnp.uint8)
    buf = buf.at[:, :msgs.shape[1]].set(msgs)
    pos = jnp.arange(n_blocks * 128, dtype=jnp.int32)[None, :]
    lens32 = lens.astype(jnp.int32)[:, None]
    buf = jnp.where(pos == lens32, jnp.uint8(0x80), buf)
    # 128-bit big-endian length: lens < 2^28 → 4 low bytes suffice.
    final_block = (lens32 + 16) // 128
    msg_bits = (lens.astype(U32) * 8)[:, None]
    len_pos = final_block * 128 + 124
    for j in range(4):
        shift = U32(8 * (3 - j))
        byte = ((msg_bits >> shift) & 0xFF).astype(jnp.uint8)
        buf = jnp.where(pos == len_pos + j, byte, buf)

    state = _init_state512(n, h0)
    out = state
    for i in range(n_blocks):
        state = compress512(
            state, _bytes_to_words512(buf[:, i * 128:(i + 1) * 128]))
        is_final = (final_block[:, 0] == i)
        out = tuple(
            (jnp.where(is_final, s[0], o[0]),
             jnp.where(is_final, s[1], o[1]))
            for s, o in zip(state, out))
    return _digest_bytes512(out, out_words)


def sha512_fixed(msgs):
    """SHA-512 of [N, L] uint8, fixed L ≤ 111 → [N, 64] uint8."""
    return _hash_fixed(msgs, _H512, 8)


def sha384_fixed(msgs):
    """SHA-384 of [N, L] uint8, fixed L ≤ 111 → [N, 48] uint8."""
    return _hash_fixed(msgs, _H384, 6)


def sha512_var(msgs, lens, max_len: int):
    """SHA-512 of [N, max_len] buffers with per-token lens → [N, 64]."""
    return _hash_var(msgs, lens, max_len, _H512, 8)


def sha384_var(msgs, lens, max_len: int):
    """SHA-384 of [N, max_len] buffers with per-token lens → [N, 48]."""
    return _hash_var(msgs, lens, max_len, _H384, 6)
