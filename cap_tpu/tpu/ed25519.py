"""Batched Ed25519 (EdDSA) verification as JAX/XLA programs.

Replaces crypto/ed25519.Verify — the reference's EdDSA hot loop
(jwt/keyset.go:126-139 → go-jose → Go stdlib) — with TPU-shaped batch
arithmetic over the limb machinery in ``bignum``:

- field arithmetic mod p = 2^255-19 in Montgomery form (16×16-bit
  limbs), batch-last [K, N] like the RSA/ECDSA engines;
- extended twisted-Edwards coordinates with the a = -1 unified
  formulas, which are COMPLETE for edwards25519 (d is non-square,
  -1 is a square mod p) — unlike the Weierstrass ladder in ``ec``,
  there are no degenerate cases and no CPU re-verification;
- the verification equation is checked the way Go does it
  (encoding comparison): compute R' = [S]B + [k](-A), normalize to
  affine with one batched Fermat inversion, re-encode, and compare
  the 32-byte encoding against the R half of the signature — which
  automatically rejects non-canonical R encodings;
- k = SHA-512(R ‖ A ‖ M) mod L is computed host-side (variable-length
  messages; hashing is cheap and branchy), S < L is enforced
  on-device (rejects the malleable S+L forgeries, as Go's
  Scalar.SetCanonicalBytes does);
- per-key precomputation: -A and B-A rows in affine triple form
  (y-x, y+x, 2dxy), gathered per token (the key-gather axis,
  SURVEY.md §2.6); keys whose 32 bytes do not decode to a curve
  point always verify False (Go returns false at decode).

Everything is shape-static; one compilation per batch-size bucket.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import limbs as L

# edwards25519 domain parameters (RFC 8032 §5.1).
P = (1 << 255) - 19
L_ORDER = (1 << 252) + 27742317777372353535851937790883648493
D_CONST = (-121665 * pow(121666, -1, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)
K = 16                       # 256 bits of 16-bit limbs
NBITS = 253                  # max bit length of S and k (both < 2^253)

_BY = 4 * pow(5, -1, P) % P


def decode_point(data: bytes) -> Optional[Tuple[int, int]]:
    """RFC 8032 §5.1.3 point decompression; None if not on the curve."""
    if len(data) != 32:
        return None
    y = int.from_bytes(data, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= P:
        return None
    y2 = y * y % P
    u = (y2 - 1) % P
    v = (D_CONST * y2 + 1) % P
    # candidate root x = (u/v)^((p+3)/8) = u·v³·(u·v⁷)^((p-5)/8)
    v3 = v * v % P * v % P
    x = u * v3 % P * pow(u * v3 % P * v3 % P * v % P, (P - 5) // 8, P) % P
    vx2 = v * x % P * x % P
    if vx2 == u:
        pass
    elif vx2 == (-u) % P:
        x = x * SQRT_M1 % P
    else:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return x, y


def _edw_add(p1: Tuple[int, int], p2: Tuple[int, int]) -> Tuple[int, int]:
    """Host affine Edwards addition (complete; table precompute only)."""
    x1, y1 = p1
    x2, y2 = p2
    dxy = D_CONST * x1 % P * x2 % P * y1 % P * y2 % P
    x3 = (x1 * y2 + y1 * x2) * pow(1 + dxy, -1, P) % P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - dxy, -1, P) % P
    return x3, y3


_B_POINT = decode_point(_BY.to_bytes(32, "little"))  # sign bit 0 → even x
assert _B_POINT is not None

_IDENTITY = (0, 1)


class _FieldConsts:
    """Cached [K, 1] device constants for the edwards25519 field."""

    def __init__(self):
        from .bignum import mont_params

        pprime, pr2, pone = mont_params(P, K)
        self.pone_int = pone
        host = dict(
            p=L.int_to_limbs(P, K),
            pp=L.int_to_limbs(pprime, K),
            pr2=L.int_to_limbs(pr2, K),
            pone=L.int_to_limbs(pone, K),
            pm2=L.int_to_limbs(P - 2, K),     # Fermat exponent
            l=L.int_to_limbs(L_ORDER, K),
        )
        b_trip = _triple_limbs(_B_POINT, pone)
        self.dev = tuple(jnp.asarray(v)[:, None] for v in (
            host["p"], host["pp"], host["pr2"], host["pone"], host["pm2"],
            host["l"], *b_trip))


def _triple_limbs(pt: Tuple[int, int], r_mod_p: int) -> List[np.ndarray]:
    """Affine point → Montgomery-form (y-x, y+x, 2dxy) limb rows."""
    x, y = pt
    vals = ((y - x) % P, (y + x) % P, 2 * D_CONST * x % P * y % P)
    return [L.int_to_limbs(v * r_mod_p % P, K) for v in vals]


_CONSTS: Optional[_FieldConsts] = None


def consts() -> _FieldConsts:
    global _CONSTS
    if _CONSTS is None:
        _CONSTS = _FieldConsts()
    return _CONSTS


class Ed25519KeyTable:
    """Device-resident table of Ed25519 public keys.

    Rows hold -A and the Shamir precompute B+(-A) as affine triples
    (y-x, y+x, 2dxy) in field-Montgomery form. Undecodable keys get
    identity rows and an ``invalid`` flag (their tokens verify False,
    matching Go's decode-failure behavior).
    """

    def __init__(self, keys: Sequence):
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        cc = consts()
        self.keys = list(keys)  # cryptography Ed25519PublicKey
        nk = len(self.keys)
        self.key_bytes: List[bytes] = [
            k.public_bytes(Encoding.Raw, PublicFormat.Raw)
            for k in self.keys]

        na = np.empty((3, nk, K), np.uint32)
        dd = np.empty((3, nk, K), np.uint32)
        invalid = np.zeros(nk, bool)
        for i, raw in enumerate(self.key_bytes):
            a = decode_point(raw)
            if a is None:
                invalid[i] = True
                neg_a = d_pt = _IDENTITY
            else:
                neg_a = ((P - a[0]) % P, a[1])
                d_pt = _edw_add(_B_POINT, neg_a)
            for t, v in enumerate(_triple_limbs(neg_a, cc.pone_int)):
                na[t, i] = v
            for t, v in enumerate(_triple_limbs(d_pt, cc.pone_int)):
                dd[t, i] = v
        self.na_tab = jnp.asarray(na)       # [3, nk, K]
        self.d_tab = jnp.asarray(dd)
        self.invalid = invalid


# ---------------------------------------------------------------------------
# Device kernel (all field values in Montgomery form unless noted)
# ---------------------------------------------------------------------------

def _edw_double(X, Y, Z, T, p, pp):
    """Extended-coordinate doubling, a = -1 (dbl-2008-hwcd). 4M+4S."""
    from . import bignum as B

    a = B.mont_mul(X, X, p, pp)
    b = B.mont_mul(Y, Y, p, pp)
    zz = B.mont_mul(Z, Z, p, pp)
    c = B.add_mod(zz, zz, p)
    d = B.sub_mod(jnp.zeros_like(a), a, p)          # a = -1 → D = -X²
    xy = B.add_mod(X, Y, p)
    e = B.sub_mod(B.sub_mod(B.mont_mul(xy, xy, p, pp), a, p), b, p)
    g = B.add_mod(d, b, p)
    f = B.sub_mod(g, c, p)
    h = B.sub_mod(d, b, p)
    return (B.mont_mul(e, f, p, pp), B.mont_mul(g, h, p, pp),
            B.mont_mul(f, g, p, pp), B.mont_mul(e, h, p, pp))


def _edw_madd(X, Y, Z, T, ym, yp, t2, p, pp):
    """Mixed extended + affine-triple addition, a = -1 (madd-2008-hwcd-3).

    7M. COMPLETE for edwards25519 — valid for every input pair,
    including doubling, inverses, and the identity on either side.
    """
    from . import bignum as B

    a = B.mont_mul(B.sub_mod(Y, X, p), ym, p, pp)
    b = B.mont_mul(B.add_mod(Y, X, p), yp, p, pp)
    c = B.mont_mul(T, t2, p, pp)
    d = B.add_mod(Z, Z, p)
    e = B.sub_mod(b, a, p)
    f = B.sub_mod(d, c, p)
    g = B.add_mod(d, c, p)
    h = B.add_mod(b, a, p)
    return (B.mont_mul(e, f, p, pp), B.mont_mul(g, h, p, pp),
            B.mont_mul(f, g, p, pp), B.mont_mul(e, h, p, pp))


@jax.jit
def _ed25519_core(s, kk, yr, sign_r, bad_key,
                  na_ym, na_yp, na_t2, d_ym, d_yp, d_t2,
                  p, pp, pr2, pone, pm2, l_, b_ym, b_yp, b_t2):
    """Batched Ed25519 verify core.

    s, kk: [K, N] plain scalar limbs (S half of the signature;
    k = H(R‖A‖M) mod L). yr: [K, N] limbs of the R encoding's y value
    (sign bit cleared); sign_r: [N] its sign bit. bad_key: [N] bool.
    na_*/d_*: [K, N] gathered per-token addend triples for -A and
    B+(-A). Remaining args: [K, 1] field constants and the basepoint
    triple (broadcast on-device — transferred once, not per batch).
    Returns ok [N].
    """
    from . import bignum as B

    shape = s.shape
    (p, pp, pr2, pone, pm2, l_, b_ym, b_yp, b_t2) = (
        jnp.broadcast_to(a, shape)
        for a in (p, pp, pr2, pone, pm2, l_, b_ym, b_yp, b_t2))

    # 1. S must be canonical: S < L (Go: Scalar.SetCanonicalBytes).
    s_ok = ~B.compare_ge(s, l_)

    # 2. Shamir ladder: R' = [S]B + [k](-A), identity start.
    zeros = jnp.zeros_like(s)
    X0, Y0, Z0, T0 = zeros, pone, pone, zeros

    def ladder_body(i, carry):
        X, Y, Z, T = carry
        bit_idx = NBITS - 1 - i
        limb = bit_idx // L.LIMB_BITS
        shift = bit_idx % L.LIMB_BITS
        b1 = ((s[limb] >> shift) & 1) > 0
        b2 = ((kk[limb] >> shift) & 1) > 0

        X, Y, Z, T = _edw_double(X, Y, Z, T, p, pp)

        both = b1 & b2
        sel = both[None, :]
        ym = jnp.where(sel, d_ym, jnp.where(b1[None, :], b_ym, na_ym))
        yp = jnp.where(sel, d_yp, jnp.where(b1[None, :], b_yp, na_yp))
        t2 = jnp.where(sel, d_t2, jnp.where(b1[None, :], b_t2, na_t2))
        Xa, Ya, Za, Ta = _edw_madd(X, Y, Z, T, ym, yp, t2, p, pp)

        has_add = (b1 | b2)[None, :]
        X = jnp.where(has_add, Xa, X)
        Y = jnp.where(has_add, Ya, Y)
        Z = jnp.where(has_add, Za, Z)
        T = jnp.where(has_add, Ta, T)
        return X, Y, Z, T

    X, Y, Z, T = lax.fori_loop(0, NBITS, ladder_body, (X0, Y0, Z0, T0))

    # 3. Affine normalize: one batched Fermat inversion of Z (Z ≠ 0
    #    always — Edwards completeness), then leave the Montgomery
    #    domain and re-encode.
    zinv = B.modexp_fixed_exponent(Z, pm2, p, pp, pr2, pone,
                                   ebits=255, exit_domain=False,
                                   s_in_mont=True)
    one = jnp.zeros_like(s).at[0].set(1)
    x = B.mont_mul(B.mont_mul(X, zinv, p, pp), one, p, pp)
    y = B.mont_mul(B.mont_mul(Y, zinv, p, pp), one, p, pp)

    # 4. Encoding comparison (Go: bytes.Equal(R, R'.Bytes())): the y
    #    limbs must match R's y field exactly and x's parity must match
    #    R's sign bit. Non-canonical yr (≥ p) can never equal y < p.
    enc_ok = jnp.all(y == yr, axis=0) & ((x[0] & 1) == sign_r)

    return s_ok & enc_ok & ~bad_key


# ---------------------------------------------------------------------------
# Host interface
# ---------------------------------------------------------------------------

def _le_bytes_to_limbs(mat: np.ndarray) -> np.ndarray:
    """[N, 32] little-endian byte rows → [K, N] limb-first array."""
    lo = mat[:, 0::2].astype(np.uint32)
    hi = mat[:, 1::2].astype(np.uint32)
    return (lo | (hi << 8)).T.copy()


def verify_ed25519_batch(table: Ed25519KeyTable, sigs: Sequence[bytes],
                         msgs: Sequence[bytes],
                         key_idx: np.ndarray) -> np.ndarray:
    """[N] bool verdicts for one EdDSA bucket.

    sigs: raw 64-byte JOSE signatures (R ‖ S); msgs: signing inputs;
    key_idx: [N] table rows. k = SHA-512(R ‖ A ‖ M) mod L is computed
    here (host), everything else on device.
    """
    n_tok = len(sigs)
    len_ok = np.fromiter((len(sg) == 64 for sg in sigs), bool, n_tok)

    sig_mat = np.zeros((n_tok, 64), np.uint8)
    k_ints: List[int] = []
    for j, sg in enumerate(sigs):
        if len_ok[j]:
            sig_mat[j] = np.frombuffer(sg, np.uint8)
            h = hashlib.sha512(
                sg[:32] + table.key_bytes[int(key_idx[j])] + msgs[j]
            ).digest()
            k_ints.append(int.from_bytes(h, "little") % L_ORDER)
        else:
            k_ints.append(0)

    s_limbs = _le_bytes_to_limbs(sig_mat[:, 32:])
    r_mat = sig_mat[:, :32].copy()
    sign_r = (r_mat[:, 31] >> 7).astype(np.uint32)
    r_mat[:, 31] &= 0x7F
    yr_limbs = _le_bytes_to_limbs(r_mat)
    k_limbs = L.ints_to_limbs(k_ints, K)

    idx = jnp.asarray(np.asarray(key_idx, np.int32))
    na = table.na_tab[:, idx].transpose(0, 2, 1)   # [3, K, N]
    dd = table.d_tab[:, idx].transpose(0, 2, 1)
    bad = jnp.asarray(table.invalid)[idx]

    ok = _ed25519_core(
        jnp.asarray(s_limbs), jnp.asarray(k_limbs),
        jnp.asarray(yr_limbs), jnp.asarray(sign_r), bad,
        na[0], na[1], na[2], dd[0], dd[1], dd[2],
        *consts().dev)
    return np.asarray(ok) & len_ok
