"""Batched Ed25519 (EdDSA) verification as JAX/XLA programs.

Replaces crypto/ed25519.Verify — the reference's EdDSA hot loop
(jwt/keyset.go:126-139 → go-jose → Go stdlib) — with TPU-shaped batch
arithmetic over the limb machinery in ``bignum``:

- field arithmetic mod p = 2^255-19 in Montgomery form (16×16-bit
  limbs), batch-last [K, N] like the RSA/ECDSA engines;
- extended twisted-Edwards coordinates with the a = -1 unified
  formulas, which are COMPLETE for edwards25519 (d is non-square,
  -1 is a square mod p) — unlike the Weierstrass ladder in ``ec``,
  there are no degenerate cases and no CPU re-verification;
- [S]B + [k](-A) by interleaved fixed-window recoding (w = 4): all
  d·2^{4i} multiples are precomputed host-side as affine triples
  (B per process, -A per key in the device-resident table), so the
  ladder is 2·64 complete mixed additions with ZERO doublings;
- the verification equation is checked the way Go does it
  (encoding comparison): compute R' = [S]B + [k](-A), normalize to
  affine with one batched Fermat inversion, re-encode, and compare
  the 32-byte encoding against the R half of the signature — which
  automatically rejects non-canonical R encodings;
- k = SHA-512(R ‖ A ‖ M) mod L is computed host-side (variable-length
  messages; hashing is cheap and branchy), S < L is enforced
  on-device (rejects the malleable S+L forgeries, as Go's
  Scalar.SetCanonicalBytes does);
- per-key precomputation: -A and B-A rows in affine triple form
  (y-x, y+x, 2dxy), gathered per token (the key-gather axis,
  SURVEY.md §2.6); keys whose 32 bytes do not decode to a curve
  point always verify False (Go returns false at decode).

Everything is shape-static; one compilation per batch-size bucket.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import limbs as L

# edwards25519 domain parameters (RFC 8032 §5.1).
P = (1 << 255) - 19
L_ORDER = (1 << 252) + 27742317777372353535851937790883648493
D_CONST = (-121665 * pow(121666, -1, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)
K = 16                       # 256 bits of 16-bit limbs
NBITS = 253                  # max bit length of S and k (both < 2^253)
N_WINDOWS = (NBITS + 3) // 4  # 4-bit interleaved-window positions

_BY = 4 * pow(5, -1, P) % P


def decode_point(data: bytes) -> Optional[Tuple[int, int]]:
    """RFC 8032 §5.1.3 point decompression; None if not on the curve."""
    if len(data) != 32:
        return None
    y = int.from_bytes(data, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= P:
        return None
    y2 = y * y % P
    u = (y2 - 1) % P
    v = (D_CONST * y2 + 1) % P
    # candidate root x = (u/v)^((p+3)/8) = u·v³·(u·v⁷)^((p-5)/8)
    v3 = v * v % P * v % P
    x = u * v3 % P * pow(u * v3 % P * v3 % P * v % P, (P - 5) // 8, P) % P
    vx2 = v * x % P * x % P
    if vx2 == u:
        pass
    elif vx2 == (-u) % P:
        x = x * SQRT_M1 % P
    else:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return x, y


def _edw_add(p1: Tuple[int, int], p2: Tuple[int, int]) -> Tuple[int, int]:
    """Host affine Edwards addition (complete; table precompute only)."""
    x1, y1 = p1
    x2, y2 = p2
    dxy = D_CONST * x1 % P * x2 % P * y1 % P * y2 % P
    x3 = (x1 * y2 + y1 * x2) * pow(1 + dxy, -1, P) % P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - dxy, -1, P) % P
    return x3, y3


_B_POINT = decode_point(_BY.to_bytes(32, "little"))  # sign bit 0 → even x
assert _B_POINT is not None

_IDENTITY = (0, 1)


def _window_triple_rows(pt: Tuple[int, int]) -> np.ndarray:
    """4-bit window table of one point as Montgomery affine triples.

    Returns [3, N_WINDOWS·16, K] uint32: row i·16 + d holds the
    (y-x, y+x, 2dxy) triple of d·2^{4i}·pt, with d = 0 the identity
    (the complete formulas absorb identity addends, so the ladder
    needs no skip mask). Low-order pt (adversarial keys) may produce
    identity rows elsewhere too — equally harmless.
    """
    cc = consts()
    rows = np.empty((3, N_WINDOWS * 16, K), np.uint32)
    base = pt
    for i in range(N_WINDOWS):
        acc = _IDENTITY
        for d in range(16):
            if d:
                acc = _edw_add(acc, base)
            for t, v in enumerate(_triple_limbs(acc, cc.pone_int)):
                rows[t, i * 16 + d] = v
        for _ in range(4):
            base = _edw_add(base, base)
    return rows


_B_TABLE = None


def b_table():
    """Cached device window table for the basepoint B: 3× [NW·16, K]."""
    global _B_TABLE
    if _B_TABLE is None:
        rows = _window_triple_rows(_B_POINT)
        _B_TABLE = tuple(jnp.asarray(rows[t]) for t in range(3))
    return _B_TABLE


class _FieldConsts:
    """Cached [K, 1] device constants for the edwards25519 field."""

    def __init__(self):
        from .bignum import mont_params

        pprime, pr2, pone = mont_params(P, K)
        self.pone_int = pone
        host = dict(
            p=L.int_to_limbs(P, K),
            pp=L.int_to_limbs(pprime, K),
            pr2=L.int_to_limbs(pr2, K),
            pone=L.int_to_limbs(pone, K),
            pm2=L.int_to_limbs(P - 2, K),     # Fermat exponent
            l=L.int_to_limbs(L_ORDER, K),
        )
        self.dev = tuple(jnp.asarray(v)[:, None] for v in (
            host["p"], host["pp"], host["pr2"], host["pone"], host["pm2"],
            host["l"]))


def _triple_limbs(pt: Tuple[int, int], r_mod_p: int) -> List[np.ndarray]:
    """Affine point → Montgomery-form (y-x, y+x, 2dxy) limb rows."""
    x, y = pt
    vals = ((y - x) % P, (y + x) % P, 2 * D_CONST * x % P * y % P)
    return [L.int_to_limbs(v * r_mod_p % P, K) for v in vals]


_CONSTS: Optional[_FieldConsts] = None


def consts() -> _FieldConsts:
    global _CONSTS
    if _CONSTS is None:
        _CONSTS = _FieldConsts()
    return _CONSTS


class Ed25519KeyTable:
    """Device-resident table of Ed25519 public keys.

    Per key, the full 4-bit interleaved-window table of -A (d·2^{4i}
    multiples as affine triples (y-x, y+x, 2dxy), field-Montgomery
    form) — the ladder then needs no doublings, only gathers + complete
    mixed adds. Undecodable keys get identity tables and an ``invalid``
    flag (their tokens verify False, matching Go's decode-failure
    behavior).
    """

    def __init__(self, keys: Sequence):
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        self.keys = list(keys)  # cryptography Ed25519PublicKey
        nk = len(self.keys)
        self.key_bytes: List[bytes] = [
            k.public_bytes(Encoding.Raw, PublicFormat.Raw)
            for k in self.keys]

        rows = N_WINDOWS * 16
        na = np.empty((3, nk * rows, K), np.uint32)
        invalid = np.zeros(nk, bool)
        for i, raw in enumerate(self.key_bytes):
            a = decode_point(raw)
            if a is None:
                invalid[i] = True
                neg_a = _IDENTITY
            else:
                neg_a = ((P - a[0]) % P, a[1])
            na[:, i * rows:(i + 1) * rows] = _window_triple_rows(neg_a)
        self.tna = tuple(jnp.asarray(na[t]) for t in range(3))
        self.invalid = invalid
        self._rns = None

    def rns(self):
        """Lazily-built RNS-form window tables (accelerator path)."""
        if self._rns is None:
            from . import ed25519_rns

            decoded = [decode_point(raw) for raw in self.key_bytes]
            self._rns = ed25519_rns.Ed25519RNSKeyTable(decoded)
        return self._rns


# ---------------------------------------------------------------------------
# Device kernel (all field values in Montgomery form unless noted)
# ---------------------------------------------------------------------------

def _edw_double(X, Y, Z, T, p, pp):
    """Extended-coordinate doubling, a = -1 (dbl-2008-hwcd). 4M+4S."""
    from . import bignum as B

    a = B.mont_mul(X, X, p, pp)
    b = B.mont_mul(Y, Y, p, pp)
    zz = B.mont_mul(Z, Z, p, pp)
    c = B.add_mod(zz, zz, p)
    d = B.sub_mod(jnp.zeros_like(a), a, p)          # a = -1 → D = -X²
    xy = B.add_mod(X, Y, p)
    e = B.sub_mod(B.sub_mod(B.mont_mul(xy, xy, p, pp), a, p), b, p)
    g = B.add_mod(d, b, p)
    f = B.sub_mod(g, c, p)
    h = B.sub_mod(d, b, p)
    return (B.mont_mul(e, f, p, pp), B.mont_mul(g, h, p, pp),
            B.mont_mul(f, g, p, pp), B.mont_mul(e, h, p, pp))


def _edw_madd(X, Y, Z, T, ym, yp, t2, p, pp):
    """Mixed extended + affine-triple addition, a = -1 (madd-2008-hwcd-3).

    7M. COMPLETE for edwards25519 — valid for every input pair,
    including doubling, inverses, and the identity on either side.
    """
    from . import bignum as B

    a = B.mont_mul(B.sub_mod(Y, X, p), ym, p, pp)
    b = B.mont_mul(B.add_mod(Y, X, p), yp, p, pp)
    c = B.mont_mul(T, t2, p, pp)
    d = B.add_mod(Z, Z, p)
    e = B.sub_mod(b, a, p)
    f = B.sub_mod(d, c, p)
    g = B.add_mod(d, c, p)
    h = B.add_mod(b, a, p)
    return (B.mont_mul(e, f, p, pp), B.mont_mul(g, h, p, pp),
            B.mont_mul(f, g, p, pp), B.mont_mul(e, h, p, pp))


@jax.jit
def _ed25519_core(s, kk, yr, sign_r, bad_key, key_idx,
                  ta_ym, ta_yp, ta_t2, tb_ym, tb_yp, tb_t2,
                  p, pp, pr2, pone, pm2, l_):
    """Batched Ed25519 verify core.

    s, kk: [K, N] plain scalar limbs (S half of the signature;
    k = H(R‖A‖M) mod L); N a power of two (batch-inverse tree).
    yr: [K, N] limbs of the R encoding's y value (sign bit cleared);
    sign_r: [N] its sign bit. bad_key: [N] bool. key_idx: [N] int32.
    ta_*: [nk·NW·16, K] per-key window tables of -A; tb_*: [NW·16, K]
    the basepoint window table. Remaining args: [K, 1] field constants
    (broadcast on-device — transferred once, not per batch).
    Returns ok [N].
    """
    from . import bignum as B

    shape = s.shape
    p1, pp1, pr21, pone1, pm21 = p, pp, pr2, pone, pm2
    (p, pp, pone, l_) = (
        jnp.broadcast_to(a, shape) for a in (p, pp, pone, l_))

    # 1. S must be canonical: S < L (Go: Scalar.SetCanonicalBytes).
    s_ok = ~B.compare_ge(s, l_)

    # 2. Interleaved-window ladder: R' = Σ d1_i·(2^{4i}B) +
    #    d2_i·(2^{4i}(-A)). Digit 0 rows hold the identity and the
    #    formulas are complete, so every iteration adds unconditionally.
    k = shape[0]

    def nibbles(u):
        return jnp.stack(
            [(u >> (4 * j)) & 15 for j in range(4)], axis=1
        ).reshape(4 * k, shape[1]).astype(jnp.int32)

    dig1 = nibbles(s)        # [S]B digits
    dig2 = nibbles(kk)       # [k](-A) digits
    key_base = key_idx.astype(jnp.int32) * (N_WINDOWS * 16)

    zeros = jnp.zeros_like(s)
    X0, Y0, Z0, T0 = zeros, pone, pone, zeros

    def add_from_table(pt, tab_ym, tab_yp, tab_t2, idx):
        X, Y, Z, T = pt
        ym = jnp.take(tab_ym, idx, axis=0).T
        yp = jnp.take(tab_yp, idx, axis=0).T
        t2 = jnp.take(tab_t2, idx, axis=0).T
        return _edw_madd(X, Y, Z, T, ym, yp, t2, p, pp)

    def ladder_body(i, carry):
        d1 = lax.dynamic_slice_in_dim(dig1, i, 1, axis=0)[0]
        d2 = lax.dynamic_slice_in_dim(dig2, i, 1, axis=0)[0]
        carry = add_from_table(carry, tb_ym, tb_yp, tb_t2, i * 16 + d1)
        carry = add_from_table(carry, ta_ym, ta_yp, ta_t2,
                               key_base + i * 16 + d2)
        return carry

    X, Y, Z, T = lax.fori_loop(0, N_WINDOWS, ladder_body,
                               (X0, Y0, Z0, T0))

    # 3. Affine normalize: batch product-tree inversion of Z (Z ≠ 0
    #    always — Edwards completeness), then leave the Montgomery
    #    domain and re-encode.
    zinv = B.batch_mont_inverse(Z, p1, pp1, pr21, pone1, pm21, nbits=255)
    one = jnp.zeros_like(s).at[0].set(1)
    x = B.mont_mul(B.mont_mul(X, zinv, p, pp), one, p, pp)
    y = B.mont_mul(B.mont_mul(Y, zinv, p, pp), one, p, pp)

    # 4. Encoding comparison (Go: bytes.Equal(R, R'.Bytes())): the y
    #    limbs must match R's y field exactly and x's parity must match
    #    R's sign bit. Non-canonical yr (≥ p) can never equal y < p.
    enc_ok = jnp.all(y == yr, axis=0) & ((x[0] & 1) == sign_r)

    return s_ok & enc_ok & ~bad_key


# ---------------------------------------------------------------------------
# Host interface
# ---------------------------------------------------------------------------

def _le_bytes_to_limbs(mat: np.ndarray) -> np.ndarray:
    """[N, 32] little-endian byte rows → [K, N] limb-first array."""
    lo = mat[:, 0::2].astype(np.uint32)
    hi = mat[:, 1::2].astype(np.uint32)
    return (lo | (hi << 8)).T.copy()


def verify_ed25519_batch_pending(table: Ed25519KeyTable,
                                 sigs: Sequence[bytes],
                                 msgs: Sequence[bytes],
                                 key_idx: np.ndarray):
    """Dispatch the EdDSA device work; return a finalize() → [N] bool.

    sigs: raw 64-byte JOSE signatures (R ‖ S); msgs: signing inputs;
    key_idx: [N] table rows. k = SHA-512(R ‖ A ‖ M) mod L is computed
    here (host), everything else on device, asynchronously.
    """
    n_tok = len(sigs)
    len_ok = np.fromiter((len(sg) == 64 for sg in sigs), bool, n_tok)

    sig_mat = np.zeros((n_tok, 64), np.uint8)
    k_ints: List[int] = []
    for j, sg in enumerate(sigs):
        if len_ok[j]:
            sig_mat[j] = np.frombuffer(sg, np.uint8)
            h = hashlib.sha512(
                sg[:32] + table.key_bytes[int(key_idx[j])] + msgs[j]
            ).digest()
            k_ints.append(int.from_bytes(h, "little") % L_ORDER)
        else:
            k_ints.append(0)

    s_limbs = _le_bytes_to_limbs(sig_mat[:, 32:])
    r_mat = sig_mat[:, :32].copy()
    sign_r = (r_mat[:, 31] >> 7).astype(np.uint32)
    r_mat[:, 31] &= 0x7F
    yr_limbs = _le_bytes_to_limbs(r_mat)
    k_limbs = L.ints_to_limbs(k_ints, K)
    key_rows = np.asarray(key_idx, np.int32)
    bad = table.invalid[key_rows]

    # Pad the batch to a power of two ≥ 128 for the inverse tree /
    # bucket-shape stability. Padding rows compute on key row 0 and are
    # discarded below.
    n_pad = 128
    while n_pad < n_tok:
        n_pad *= 2
    if n_pad != n_tok:
        fill = n_pad - n_tok
        s_limbs = np.pad(s_limbs, ((0, 0), (0, fill)))
        k_limbs = np.pad(k_limbs, ((0, 0), (0, fill)))
        yr_limbs = np.pad(yr_limbs, ((0, 0), (0, fill)))
        sign_r = np.pad(sign_r, (0, fill))
        key_rows = np.pad(key_rows, (0, fill))
        bad = np.pad(bad, (0, fill))

    from .rns import use_rns

    if use_rns():
        from . import ed25519_rns

        rtab = table.rns()
        ok_dev = ed25519_rns._ed25519_rns_core(
            jnp.asarray(s_limbs), jnp.asarray(k_limbs),
            jnp.asarray(yr_limbs), jnp.asarray(sign_r), jnp.asarray(bad),
            jnp.asarray(key_rows),
            *rtab.tna, *ed25519_rns.b_table_rns(),
            *consts().dev)
    else:
        ok_dev = _ed25519_core(
            jnp.asarray(s_limbs), jnp.asarray(k_limbs),
            jnp.asarray(yr_limbs), jnp.asarray(sign_r), jnp.asarray(bad),
            jnp.asarray(key_rows),
            *table.tna, *b_table(),
            *consts().dev)
    return lambda: np.asarray(ok_dev)[:n_tok] & len_ok


def verify_ed25519_batch(table: Ed25519KeyTable, sigs: Sequence[bytes],
                         msgs: Sequence[bytes],
                         key_idx: np.ndarray) -> np.ndarray:
    """[N] bool verdicts for one EdDSA bucket (synchronous wrapper)."""
    return verify_ed25519_batch_pending(table, sigs, msgs, key_idx)()


# ---------------------------------------------------------------------------
# Packed single-transfer dispatch (see rsa.py's packed section)
# ---------------------------------------------------------------------------

ED_REC_EXTRA = 2          # trailing bytes per record: flags, key row


def ed_packed_records(table: Ed25519KeyTable, sigs: Sequence[bytes],
                      msgs: Sequence[bytes],
                      key_idx: np.ndarray) -> np.ndarray:
    """Host: packed [N, 64 + 32 + 2] u8 records for one EdDSA chunk.

    Row layout: signature R‖S (64) ‖ k = SHA-512(R‖A‖M) mod L as 32
    little-endian bytes ‖ validity flag u8 (length ok AND key decodes)
    ‖ key row u8. The k hash is inherently host-side (variable-length
    message); everything downstream of it runs on device.
    """
    n = len(sigs)
    rec = np.zeros((n, 64 + 32 + ED_REC_EXTRA), np.uint8)
    chunks: List[bytes] = []
    live: List[int] = []
    for j, sg in enumerate(sigs):
        row = int(key_idx[j])
        rec[j, 97] = row
        if len(sg) == 64:
            rec[j, :64] = np.frombuffer(sg, np.uint8)
            rec[j, 96] = not table.invalid[row]
            chunks.append(sg[:32] + table.key_bytes[row] + msgs[j])
            live.append(j)
    if not live:
        return rec
    # k = SHA-512(R ‖ A ‖ M): multithreaded C++ when built
    digests = _sha512_batch(chunks)
    for j, h in zip(live, digests):
        kk = int.from_bytes(h, "little") % L_ORDER
        rec[j, 64:96] = np.frombuffer(kk.to_bytes(32, "little"),
                                      np.uint8)
    return rec


def _sha512_batch(chunks: Sequence[bytes]) -> List[bytes]:
    from ..runtime import prep

    native = prep._load_native()
    if native is not None:
        return native.sha_batch(chunks, 512)
    return [hashlib.sha512(c).digest() for c in chunks]


def _le_bytes_to_limbs_dev(mat):
    """Device: [N, 2K] u8 little-endian → [K, N] u32 limbs."""
    m = mat.astype(jnp.uint32)
    return (m[:, 0::2] | (m[:, 1::2] << 8)).T


def _ed_packed_unpack(packed):
    sig = packed[:, :64]
    flags = packed[:, 96] != 0
    idx = packed[:, 97].astype(jnp.int32)
    sign_r = (sig[:, 31] >> 7).astype(jnp.uint32)
    r_clr = sig[:, :32].at[:, 31].set(sig[:, 31] & 0x7F)
    yr = _le_bytes_to_limbs_dev(r_clr)
    s = _le_bytes_to_limbs_dev(sig[:, 32:64])
    kk = _le_bytes_to_limbs_dev(packed[:, 64:96])
    bad = jnp.zeros(packed.shape[0], bool)   # folded into flags on host
    return s, kk, yr, sign_r, bad, idx, flags


def _ed_packed_rns_impl(packed, ta, tb, cdev):
    from . import ed25519_rns

    s, kk, yr, sign_r, bad, idx, flags = _ed_packed_unpack(packed)
    p, pp, pr2, pone, pm2, l_ = cdev
    ok = ed25519_rns._ed25519_rns_core(
        s, kk, yr, sign_r, bad, idx, *ta, *tb, p, pp, pr2, pone, pm2, l_)
    return ok & flags


def _ed_packed_limb_impl(packed, ta, tb, cdev):
    s, kk, yr, sign_r, bad, idx, flags = _ed_packed_unpack(packed)
    p, pp, pr2, pone, pm2, l_ = cdev
    ok = _ed25519_core(
        s, kk, yr, sign_r, bad, idx, *ta, *tb, p, pp, pr2, pone, pm2, l_)
    return ok & flags


_ed_packed_jits: Dict[str, object] = {}


def _ed_packed_jit(name: str, impl):
    fn = _ed_packed_jits.get(name)
    if fn is None:
        fn = jax.jit(impl)
        _ed_packed_jits[name] = fn
    return fn


def verify_ed_packed_pending(table: Ed25519KeyTable, rec: np.ndarray,
                             mesh=None):
    """Dispatch one packed EdDSA chunk; returns the device [N] bool.

    With a mesh the record shards along the batch axis; tables
    replicate (SURVEY.md §2.6).
    """
    from .rns import use_rns

    if mesh is not None:
        from ..parallel.place import replicated, shard_batch

        dev = shard_batch(mesh, rec)
        place = lambda a: replicated(mesh, a)  # noqa: E731
    else:
        dev = jax.device_put(rec)
        place = lambda a: a  # noqa: E731
    if use_rns():
        from . import ed25519_rns

        rtab = table.rns()
        fn = _ed_packed_jit("rns", _ed_packed_rns_impl)
        return fn(dev, tuple(place(a) for a in rtab.tna),
                  tuple(place(a) for a in ed25519_rns.b_table_rns()),
                  tuple(place(a) for a in consts().dev))
    fn = _ed_packed_jit("limb", _ed_packed_limb_impl)
    return fn(dev, tuple(place(a) for a in table.tna),
              tuple(place(a) for a in b_table()),
              tuple(place(a) for a in consts().dev))
