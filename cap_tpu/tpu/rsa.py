"""Batched RSA signature verification on TPU.

Replaces crypto/rsa.VerifyPKCS1v15 / VerifyPSS (the reference's hot
loop, jwt/keyset.go:126-139 → go-jose → Go stdlib) with:

- a device-resident key table (moduli + Montgomery constants as limb
  arrays) built once per KeySet/JWKS — the "key-gather parallelism"
  axis from SURVEY.md §2.6: per-token kid indices gather rows;
- one batched modexp over the whole bucket (fast path e=65537, generic
  ladder otherwise);
- PKCS#1 v1.5: the full expected encoded message EM is constructed
  host-side with vectorized numpy (variable per-token key sizes
  supported — mixed 2048/4096 JWKS), compared on device, only a [N]
  bool mask returns to host;
- PSS: modexp on device, EM returned to host, MGF1/salt check per
  token (hashlib; the C++ runtime batches this later).

Bit-exact parity contract: a token verifies here iff it verifies on the
CPU oracle — including rejections (wrong length, s >= n, bad padding,
wrong hash).
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

import numpy as np

from . import limbs as L

# ASN.1 DigestInfo prefixes (RFC 8017 §9.2 notes).
DIGEST_INFO_PREFIX = {
    "sha256": bytes.fromhex("3031300d060960864801650304020105000420"),
    "sha384": bytes.fromhex("3041300d060960864801650304020205000430"),
    "sha512": bytes.fromhex("3051300d060960864801650304020305000440"),
}
HASH_LEN = {"sha256": 32, "sha384": 48, "sha512": 64}


from .limbs import bytes_to_limbs_device


def _expected_em_device(dig, sizes, k: int, hash_name: str):
    """Device construction of the PKCS#1 v1.5 expected EM limbs.

    dig: [N, hlen] u8 digests; sizes: [N] i32 per-token emLen. Builds
    EM = 00 01 FF.. 00 DigestInfo ‖ H right-aligned in [N, 2k] bytes —
    entirely on device, so only the digest crosses the wire.
    """
    import jax.numpy as jnp

    prefix = DIGEST_INFO_PREFIX[hash_name]
    h_len = HASH_LEN[hash_name]
    t_len = len(prefix) + h_len
    width = 2 * k
    n = dig.shape[0]
    cols = jnp.arange(width, dtype=jnp.int32)[None, :]
    start = (width - sizes.astype(jnp.int32))[:, None]
    val = jnp.zeros((n, width), jnp.uint8)
    val = jnp.where(cols == start + 1, jnp.uint8(1), val)
    val = jnp.where((cols >= start + 2) & (cols < width - t_len - 1),
                    jnp.uint8(0xFF), val)
    pref = jnp.asarray(np.frombuffer(prefix, np.uint8))
    val = val.at[:, width - t_len: width - h_len].set(pref[None, :])
    val = val.at[:, width - h_len:].set(dig)
    return bytes_to_limbs_device(val)


def _use_rns() -> bool:
    from .rns import use_rns

    return use_rns()


class RSAKeyTable:
    """Device-resident table of RSA public keys in Montgomery form.

    All keys are padded to a common limb count K (Montgomery with
    R = 2^(16K) works for any n < R), so one compiled modexp serves a
    mixed-size JWKS.
    """

    def __init__(self, public_numbers: Sequence, k: Optional[int] = None):
        """public_numbers: list of (n_int, e_int)."""
        import jax.numpy as jnp

        self.n_ints = [n for n, _ in public_numbers]
        self.e_ints = [e for _, e in public_numbers]
        self.sizes_bytes = [(n.bit_length() + 7) // 8 for n in self.n_ints]
        need = L.nlimbs_for_bits(max(n.bit_length() for n in self.n_ints))
        # One spare limb beyond the modulus width → R ≥ 2^16·n ≥ 4n, the
        # precondition for the subtraction-free Montgomery chain.
        self.k = k if k is not None else max(need + 1, 8)
        if self.k <= need:
            raise ValueError("k too small for lazy Montgomery headroom")

        nk = len(self.n_ints)
        n_tab = np.empty((nk, self.k), np.uint32)
        np_tab = np.empty((nk, self.k), np.uint32)
        r2_tab = np.empty((nk, self.k), np.uint32)
        one_tab = np.empty((nk, self.k), np.uint32)
        from .bignum import mont_params

        for i, n in enumerate(self.n_ints):
            nprime, r2, one_m = mont_params(n, self.k)
            n_tab[i] = L.int_to_limbs(n, self.k)
            np_tab[i] = L.int_to_limbs(nprime, self.k)
            r2_tab[i] = L.int_to_limbs(r2, self.k)
            one_tab[i] = L.int_to_limbs(one_m, self.k)
        # Rows gathered per token then transposed to limb-first on device.
        self.n_tab = jnp.asarray(n_tab)
        self.np_tab = jnp.asarray(np_tab)
        self.r2_tab = jnp.asarray(r2_tab)
        self.one_tab = jnp.asarray(one_tab)
        self.e_arr = np.asarray(self.e_ints, np.uint32)
        self.all_f4 = all(e == 65537 for e in self.e_ints)
        self.max_ebits = max(e.bit_length() for e in self.e_ints)
        # Device-resident per-key scalars for the packed in-jit gathers.
        self.sizes_dev = jnp.asarray(self.sizes_bytes, jnp.int32)
        self.e_dev = jnp.asarray(self.e_arr)
        self.mod_bits_dev = jnp.asarray(
            [n.bit_length() for n in self.n_ints], jnp.int32)
        self._rns = None

    def rns(self):
        """Lazily-built RNS engine (ctx + per-key table); e=65537 only.

        Context bit-width rounds up to a 256-bit grid so mixed-size
        JWKS reuse cached contexts.
        """
        if self._rns is None:
            from . import rns as rns_mod

            nbits = max(n.bit_length() for n in self.n_ints)
            nbits = ((nbits + 255) // 256) * 256
            try:
                ctx = rns_mod.context(nbits, self.k)
                self._rns = (ctx, rns_mod.RNSKeyTable(ctx, self.n_ints))
            except rns_mod.RNSUnsupportedKey:
                self._rns = (None, None)   # degenerate key → limb path
        return self._rns


def _gather_limb_first(tab, idx):
    """[nk, K] table + [N] indices → [K, N] device array."""
    return tab[idx].T


def modexp_for_table(table: RSAKeyTable, s_limbs, key_idx: np.ndarray):
    """Batched s^e mod n for tokens hitting ``table``; returns [K, N] EM limbs.

    s_limbs: [K, N] numpy/jax signature integers; key_idx: [N] int32.
    """
    import jax.numpy as jnp

    from . import bignum

    idx = jnp.asarray(key_idx, jnp.int32)
    s = jnp.asarray(s_limbs)
    n = _gather_limb_first(table.n_tab, idx)
    nprime = _gather_limb_first(table.np_tab, idx)
    r2 = _gather_limb_first(table.r2_tab, idx)
    if table.all_f4:
        return bignum.modexp_65537(s, n, nprime, r2)
    one_m = _gather_limb_first(table.one_tab, idx)
    e = jnp.asarray(table.e_arr, jnp.uint32)[idx]
    return bignum.modexp_vare(s, e, n, nprime, r2, one_m,
                              ebits=table.max_ebits)


def s_in_range_mask(table: RSAKeyTable, s_limbs, key_idx: np.ndarray):
    """[N] bool: signature integer s < n (RFC 8017 step 1 range check)."""
    import jax.numpy as jnp

    from . import bignum

    idx = jnp.asarray(key_idx, jnp.int32)
    n = _gather_limb_first(table.n_tab, idx)
    s = jnp.asarray(s_limbs)
    return ~bignum.compare_ge(s, n)


def expected_pkcs1v15_em(hashes_: Sequence[bytes], hash_name: str,
                         em_lens: np.ndarray, k: int) -> np.ndarray:
    """Vectorized construction of the expected PKCS#1 v1.5 EM per token.

    EM = 0x00 0x01 [0xFF × (emLen − tLen − 3)] 0x00 DigestInfo ‖ H,
    right-aligned in a [N, 2k]-byte matrix → [k, N] limb array.
    """
    n = len(hashes_)
    width = 2 * k
    prefix = DIGEST_INFO_PREFIX[hash_name]
    h_len = HASH_LEN[hash_name]
    t_len = len(prefix) + h_len
    buf = np.zeros((n, width), np.uint8)
    cols = np.arange(width)[None, :]
    starts = width - em_lens[:, None]            # first EM byte per token
    ff_lo = starts + 2
    ff_hi = width - t_len - 1                    # exclusive of 0x00 separator
    buf[(cols >= ff_lo) & (cols < ff_hi)] = 0xFF
    rows = np.arange(n)
    buf[rows, (starts[:, 0] + 1)] = 0x01
    buf[:, width - t_len - 1] = 0x00
    tail = np.frombuffer(prefix, np.uint8)[None, :].repeat(n, 0)
    buf[:, width - t_len: width - h_len] = tail
    hmat = np.zeros((n, h_len), np.uint8)
    for j, h in enumerate(hashes_):
        hmat[j] = np.frombuffer(h, np.uint8)
    buf[:, width - h_len:] = hmat
    hi = buf[:, 0::2].astype(np.uint32)
    lo = buf[:, 1::2].astype(np.uint32)
    limbs_be = (hi << 8) | lo
    return limbs_be[:, ::-1].T.copy()            # [k, N] little-endian


def expected_pkcs1v15_em_mat(hash_mat: np.ndarray, hash_name: str,
                             em_lens: np.ndarray, k: int) -> np.ndarray:
    """Like expected_pkcs1v15_em but takes a [N, hlen] digest matrix."""
    n = hash_mat.shape[0]
    width = 2 * k
    prefix = DIGEST_INFO_PREFIX[hash_name]
    h_len = HASH_LEN[hash_name]
    t_len = len(prefix) + h_len
    buf = np.zeros((n, width), np.uint8)
    cols = np.arange(width)[None, :]
    starts = width - em_lens[:, None]
    ff_lo = starts + 2
    ff_hi = width - t_len - 1
    buf[(cols >= ff_lo) & (cols < ff_hi)] = 0xFF
    buf[np.arange(n), (starts[:, 0] + 1)] = 0x01
    buf[:, width - t_len - 1] = 0x00
    buf[:, width - t_len: width - h_len] = np.frombuffer(prefix, np.uint8)
    buf[:, width - h_len:] = hash_mat[:, :h_len]
    hi = buf[:, 0::2].astype(np.uint32)
    lo = buf[:, 1::2].astype(np.uint32)
    return ((hi << 8) | lo)[:, ::-1].T.copy()


def verify_pkcs1v15_arrays_pending(table: RSAKeyTable, sig_mat: np.ndarray,
                                   sig_lens: np.ndarray,
                                   hash_mat: np.ndarray, hash_name: str,
                                   key_idx: np.ndarray):
    """Dispatch the RS* device work; return a finalize() → [N] bool.

    Dispatch is asynchronous — callers can launch every bucket's device
    program before the first materializing sync (one ~RTT to the
    accelerator instead of one per bucket).
    """
    import jax.numpy as jnp

    from . import bignum  # noqa: F401

    sizes = np.asarray(table.sizes_bytes, np.int64)[key_idx]
    len_ok = sig_lens == sizes
    em_len_ok = sizes >= len(DIGEST_INFO_PREFIX[hash_name]) + \
        HASH_LEN[hash_name] + 11
    host_mask = len_ok & em_len_ok
    # Wire-minimal H2D: raw right-aligned signature bytes + digests +
    # per-token sizes; limb conversion and expected-EM construction run
    # on device (_rs_prep).
    safe_lens = np.where(len_ok, sig_lens, 0)
    aligned = L.right_align_bytes(
        np.where(len_ok[:, None], sig_mat, 0), safe_lens, 2 * table.k)
    h_len = HASH_LEN[hash_name]
    dig = np.ascontiguousarray(hash_mat[:, :h_len])
    s_limbs, expected = _rs_prep(
        jnp.asarray(aligned), jnp.asarray(dig),
        jnp.asarray(sizes, jnp.int32), k=table.k, hash_name=hash_name)
    in_range = s_in_range_mask(table, s_limbs, key_idx)
    if table.all_f4 and _use_rns():
        # MXU path: modexp + EM compare entirely in RNS form.
        from . import rns as rns_mod

        ctx, rtab = table.rns()
        if ctx is not None:
            eq = rns_mod.verify_em_equals_device(
                ctx, rtab, s_limbs, expected, key_idx)
            return lambda: np.asarray(eq & in_range) & host_mask
    em = modexp_for_table(table, s_limbs, key_idx)
    eq = jnp.all(em == expected, axis=0) & in_range
    return lambda: np.asarray(eq) & host_mask


def _rs_prep_impl(sig_bytes, dig, sizes, k: int, hash_name: str):
    return (bytes_to_limbs_device(sig_bytes),
            _expected_em_device(dig, sizes, k, hash_name))


_rs_prep_cache: dict = {}


def _rs_prep(sig_bytes, dig, sizes, k: int, hash_name: str):
    """Jitted device prep: sig bytes → limbs, digest → expected EM."""
    import jax

    key = "rs_prep"
    fn = _rs_prep_cache.get(key)
    if fn is None:
        fn = jax.jit(_rs_prep_impl, static_argnames=("k", "hash_name"))
        _rs_prep_cache[key] = fn
    return fn(sig_bytes, dig, sizes, k=k, hash_name=hash_name)


def verify_pkcs1v15_arrays(table: RSAKeyTable, sig_mat: np.ndarray,
                           sig_lens: np.ndarray, hash_mat: np.ndarray,
                           hash_name: str,
                           key_idx: np.ndarray) -> np.ndarray:
    """Array-native RS* verify: [N] bool verdicts, no per-token Python.

    sig_mat: [N, W] left-aligned signature bytes; sig_lens: [N];
    hash_mat: [N, ≥hlen] digests; key_idx: [N] table rows.
    """
    return verify_pkcs1v15_arrays_pending(
        table, sig_mat, sig_lens, hash_mat, hash_name, key_idx)()


def _limbs_to_bytes_impl(limbs):
    """Device: [K, N] u32 16-bit limbs → [N, 2K] u8 big-endian bytes."""
    import jax.numpy as jnp

    be = limbs.T[:, ::-1]
    hi = (be >> 8).astype(jnp.uint8)
    lo = (be & 0xFF).astype(jnp.uint8)
    return jnp.stack([hi, lo], axis=2).reshape(be.shape[0], -1)


_limbs_to_bytes_jit = None


def _limbs_to_bytes_dev(limbs):
    global _limbs_to_bytes_jit
    if _limbs_to_bytes_jit is None:
        import jax

        _limbs_to_bytes_jit = jax.jit(_limbs_to_bytes_impl)
    return _limbs_to_bytes_jit(limbs)


def verify_pss_arrays_pending(table: RSAKeyTable, sig_mat: np.ndarray,
                              sig_lens: np.ndarray, hash_mat: np.ndarray,
                              hash_name: str, key_idx: np.ndarray):
    """Dispatch the PS* modexp; finalize() runs the host EM/MGF1 check."""
    import jax.numpy as jnp

    n_tok = sig_mat.shape[0]
    sizes = np.asarray(table.sizes_bytes, np.int64)[key_idx]
    mod_bits = np.asarray([n.bit_length() for n in table.n_ints])[key_idx]
    len_ok = sig_lens == sizes
    safe_lens = np.where(len_ok, sig_lens, 0)
    aligned = L.right_align_bytes(
        np.where(len_ok[:, None], sig_mat, 0), safe_lens, 2 * table.k)
    s_limbs = bytes_to_limbs_device(jnp.asarray(aligned))
    if table.all_f4 and _use_rns():
        from . import rns as rns_mod

        ctx, rtab = table.rns()
        if ctx is not None:
            idx = jnp.asarray(key_idx, jnp.int32)
            n_gath = table.n_tab[idx].T
            em_dev = rns_mod.modexp_em_device(ctx, rtab, s_limbs,
                                              key_idx, n_gath)
        else:
            em_dev = modexp_for_table(table, s_limbs, key_idx)
    else:
        em_dev = modexp_for_table(table, s_limbs, key_idx)
    in_range_dev = s_in_range_mask(table, s_limbs, key_idx)
    # D2H diet: ship the EM back as [N, 2k] u8 BYTES (packed on device)
    # instead of [K, N] u32 limbs — half the wire bytes on the return
    # path, which dominates the PS* configs.
    em_bytes_dev = _limbs_to_bytes_dev(em_dev)

    def finalize() -> np.ndarray:
        in_range = np.asarray(in_range_dev)
        valid = len_ok & in_range
        em_mat = np.asarray(em_bytes_dev)
        h_len = HASH_LEN[hash_name]

        from ..runtime import prep

        native = prep._load_native()
        if native is not None:
            ok = native.pss_check_batch(
                em_mat, hash_mat[:, :h_len], mod_bits - 1,
                8 * h_len, valid)
            if ok is not None:
                return ok
        out = np.zeros(n_tok, bool)
        for j in range(n_tok):
            if not valid[j]:
                continue
            out[j] = pss_check_em(em_mat[j].tobytes(),
                                  hash_mat[j, :h_len].tobytes(),
                                  int(mod_bits[j]) - 1, hash_name)
        return out

    return finalize


def verify_pss_arrays(table: RSAKeyTable, sig_mat: np.ndarray,
                      sig_lens: np.ndarray, hash_mat: np.ndarray,
                      hash_name: str, key_idx: np.ndarray) -> np.ndarray:
    """Array-native PS* verify: device modexp, host EM/MGF1 check."""
    return verify_pss_arrays_pending(table, sig_mat, sig_lens, hash_mat,
                                     hash_name, key_idx)()


def verify_pkcs1v15_batch(table: RSAKeyTable, sigs: Sequence[bytes],
                          msg_hashes: Sequence[bytes], hash_name: str,
                          key_idx: np.ndarray) -> np.ndarray:
    """[N] bool verdicts for one RS* bucket. Tokens whose signature length
    doesn't match their key size fail without touching the device."""
    import jax.numpy as jnp

    from . import bignum  # noqa: F401  (jit caches live there)

    n_tok = len(sigs)
    sizes = np.asarray([table.sizes_bytes[i] for i in key_idx])
    len_ok = np.asarray([len(s) for s in sigs]) == sizes
    em_len_ok = sizes >= len(DIGEST_INFO_PREFIX[hash_name]) + \
        HASH_LEN[hash_name] + 11
    s_limbs = L.bytes_be_to_limbs(
        [s if ok else b"" for s, ok in zip(sigs, len_ok)], table.k
    )
    expected_np = expected_pkcs1v15_em(msg_hashes, hash_name, sizes,
                                       table.k)
    in_range = s_in_range_mask(table, s_limbs, key_idx)
    if table.all_f4 and _use_rns():
        from . import rns as rns_mod

        ctx, rtab = table.rns()
        if ctx is not None:
            eq = rns_mod.verify_em_equals(ctx, rtab, s_limbs, expected_np,
                                          np.asarray(key_idx, np.int32))
            return eq & np.asarray(in_range) & len_ok & em_len_ok
    em = modexp_for_table(table, s_limbs, key_idx)
    eq = jnp.all(em == jnp.asarray(expected_np), axis=0)
    ok = np.asarray(eq & in_range)
    return ok & len_ok & em_len_ok


def _mgf1(seed: bytes, mask_len: int, hash_name: str) -> bytes:
    h_len = HASH_LEN[hash_name]
    out = bytearray()
    for counter in range((mask_len + h_len - 1) // h_len):
        out += hashlib.new(hash_name,
                           seed + counter.to_bytes(4, "big")).digest()
    return bytes(out[:mask_len])


def pss_check_em(em: bytes, m_hash: bytes, em_bits: int,
                 hash_name: str, salt_len: Optional[int] = None) -> bool:
    """EMSA-PSS-VERIFY (RFC 8017 §9.1.2) for one token, on the host.

    salt_len None → auto-recover (any salt length), matching the CPU
    oracle's PSS.AUTO verification.
    """
    h_len = HASH_LEN[hash_name]
    em_len = (em_bits + 7) // 8
    if len(em) > em_len:
        # EM must be < 2^emBits: any dropped high bytes must be zero.
        if any(em[: len(em) - em_len]):
            return False
        em = em[-em_len:]
    if em_len < h_len + 2:
        return False
    if em[-1] != 0xBC:
        return False
    masked_db = em[: em_len - h_len - 1]
    h = em[em_len - h_len - 1: em_len - 1]
    db_len = em_len - h_len - 1
    unused_bits = 8 * em_len - em_bits
    if unused_bits and masked_db[0] >> (8 - unused_bits):
        return False
    db_mask = _mgf1(h, db_len, hash_name)
    db = bytes(a ^ b for a, b in zip(masked_db, db_mask))
    if unused_bits:
        db = bytes([db[0] & (0xFF >> unused_bits)]) + db[1:]
    # DB = PS(0x00..) ‖ 0x01 ‖ salt
    sep = db.find(b"\x01")
    if sep == -1 or any(db[:sep]):
        return False
    salt = db[sep + 1:]
    if salt_len is not None and len(salt) != salt_len:
        return False
    m_prime = b"\x00" * 8 + m_hash + salt
    return hashlib.new(hash_name, m_prime).digest() == h


def verify_pss_batch(table: RSAKeyTable, sigs: Sequence[bytes],
                     msg_hashes: Sequence[bytes], hash_name: str,
                     key_idx: np.ndarray) -> np.ndarray:
    """[N] bool verdicts for one PS* bucket: device modexp + host EM check."""
    n_tok = len(sigs)
    sizes = np.asarray([table.sizes_bytes[i] for i in key_idx])
    mod_bits = np.asarray([table.n_ints[i].bit_length() for i in key_idx])
    len_ok = np.asarray([len(s) for s in sigs]) == sizes
    s_limbs = L.bytes_be_to_limbs(
        [s if ok else b"" for s, ok in zip(sigs, len_ok)], table.k
    )
    em_dev = modexp_for_table(table, s_limbs, key_idx)
    in_range = np.asarray(s_in_range_mask(table, s_limbs, key_idx))
    em_bytes = L.limbs_to_bytes_be(np.asarray(em_dev), 2 * table.k)
    out = np.zeros(n_tok, bool)
    for j in range(n_tok):
        if not (len_ok[j] and in_range[j]):
            continue
        em_bits = int(mod_bits[j]) - 1
        out[j] = pss_check_em(em_bytes[j], msg_hashes[j], em_bits, hash_name)
    return out


# ---------------------------------------------------------------------------
# Device-side EMSA-PSS-VERIFY (SHA-256/384/512)
# ---------------------------------------------------------------------------

def _pss_hash_fns(hash_name: str):
    """(fixed_fn, var_fn, h_len) for the device PSS hashing."""
    if hash_name == "sha256":
        from . import sha256 as S

        return S.sha256_fixed, S.sha256_var, 32
    from . import sha512 as S

    if hash_name == "sha384":
        return S.sha384_fixed, S.sha384_var, 48
    if hash_name == "sha512":
        return S.sha512_fixed, S.sha512_var, 64
    raise ValueError(f"unsupported PSS hash {hash_name!r}")


def _vshift_left(mat, sh, max_shift: int):
    """out[i, j] = mat[i, j + sh[i]] (zero fill), sh ∈ [0, max_shift].

    Binary-decomposed variable shift: log2 masked STATIC slices. A
    per-token ``take_along_axis`` byte gather here measured ~40 ms per
    call @16k on chip (u8 lane gathers scalarize); these ~9 selects
    are plain elementwise traffic (docs/PERF.md r5 PSS section).
    """
    import jax.numpy as jnp

    n = mat.shape[0]
    x = mat
    bits = max(1, int(max_shift).bit_length())
    for b in range(bits):
        step = 1 << b
        if step > max_shift:
            break
        shifted = jnp.concatenate(
            [x[:, step:], jnp.zeros((n, step), x.dtype)], axis=1)
        x = jnp.where((sh[:, None] & step) != 0, shifted, x)
    return x


def _pss_verify_device(em_bytes, mhash, mod_bits, *, width: int,
                       hash_name: str):
    """RFC 8017 §9.1.2 on device, salt auto-recovered: [N] bool.

    em_bytes: [N, width] big-endian EM integer bytes (width = 2k);
    mhash: [N, h_len] u8; mod_bits: [N] i32 per-token modulus bits.
    The MGF1 digests and H' run as batched device hashing
    (tpu/sha256.py, tpu/sha512.py — all three PS* families), so NO EM
    bytes ever leave the device; the reference computes all of this
    per token on CPU (jwt/keyset.go:126-139 → crypto/rsa.VerifyPSS).
    All per-token-offset extraction uses _vshift_left — no dynamic
    gathers anywhere.

    Bit-exact with pss_check_em/cap_pss_check_batch: every structural
    rejection (short emLen, missing 0xBC, nonzero leading bits/bytes,
    bad PS/0x01 separator, H' mismatch) reproduces the host verdicts.
    """
    import jax.numpy as jnp

    sha_fixed, sha_var, h_len = _pss_hash_fns(hash_name)

    n = em_bytes.shape[0]
    em_bits = mod_bits.astype(jnp.int32) - 1
    em_len = (em_bits + 7) // 8                     # [N]
    start = width - em_len                          # first EM byte
    cols = jnp.arange(width, dtype=jnp.int32)[None, :]

    # EM < 2^emBits: bytes before `start` must be zero.
    lead_ok = jnp.all(jnp.where(cols < start[:, None], em_bytes, 0) == 0,
                      axis=1)
    db_len = em_len - h_len - 1                     # [N]
    len_ok = em_len >= h_len + 2
    trailer_ok = em_bytes[:, width - 1] == 0xBC

    # H and maskedDB, extracted at per-token offsets (variable shift).
    h_mat = em_bytes[:, width - 1 - h_len: width - 1]       # [N, h_len]
    db_max = width - h_len - 1
    dbj = jnp.arange(db_max, dtype=jnp.int32)[None, :]
    start_c = jnp.clip(start, 0, width)
    masked_db = _vshift_left(em_bytes, start_c, width)[:, :db_max]
    in_db = dbj < db_len[:, None]
    masked_db = jnp.where(in_db, masked_db, 0)

    unused = 8 * em_len - em_bits                   # [N] ∈ [0, 7]
    top_mask = (0xFF >> unused).astype(jnp.uint8)   # [N]
    top_ok = (unused == 0) | \
        ((masked_db[:, 0] >> (8 - unused).astype(jnp.uint8)) == 0)

    # MGF1(H, dbLen): ceil(db_max/h_len) fixed-size single-block
    # hashes; mask byte j = Hash(H ‖ be32(j // h_len))[j % h_len].
    n_ctr = (db_max + h_len - 1) // h_len
    seeds = jnp.zeros((n, h_len + 4), jnp.uint8)
    seeds = seeds.at[:, :h_len].set(h_mat)
    mask_parts = []
    for ctr in range(n_ctr):
        s = seeds.at[:, h_len + 3].set(jnp.uint8(ctr & 0xFF))
        s = s.at[:, h_len + 2].set(jnp.uint8((ctr >> 8) & 0xFF))
        mask_parts.append(sha_fixed(s))
    mask = jnp.concatenate(mask_parts, axis=1)[:, :db_max]
    db = masked_db ^ jnp.where(in_db, mask, 0)
    db = db.at[:, 0].set(db[:, 0] & top_mask)

    # DB = 0x00.. ‖ 0x01 ‖ salt: first nonzero byte must be 0x01.
    nz = (db != 0) & in_db
    sep = jnp.argmax(nz, axis=1).astype(jnp.int32)  # 0 when none
    any_nz = jnp.any(nz, axis=1)
    sep_byte = jnp.sum(
        jnp.where(dbj == sep[:, None], db.astype(jnp.int32), 0), axis=1)
    sep_ok = any_nz & (sep_byte == 1)
    salt_len = db_len - sep - 1                     # [N]

    # M' = 0^8 ‖ mHash ‖ salt; salt = db shifted left by sep+1.
    salt_max = db_max - 1
    mp_len = 8 + h_len + salt_len
    mp_max = 8 + h_len + salt_max
    sj = jnp.arange(salt_max, dtype=jnp.int32)[None, :]
    salt = _vshift_left(db, sep + 1, db_max)[:, :salt_max]
    salt = jnp.where(sj < salt_len[:, None], salt, 0)
    mprime = jnp.zeros((n, mp_max), jnp.uint8)
    mprime = mprime.at[:, 8:8 + h_len].set(mhash[:, :h_len])
    mprime = mprime.at[:, 8 + h_len:].set(salt)
    hprime = sha_var(mprime, mp_len, mp_max)

    h_ok = jnp.all(hprime[:, :h_len] == h_mat, axis=1)
    return (lead_ok & len_ok & trailer_ok & top_ok & sep_ok & h_ok &
            (db_len > 0))


# ---------------------------------------------------------------------------
# Packed single-transfer dispatch (the H2D-pipelined hot path)
# ---------------------------------------------------------------------------
#
# The tunnel probe (tools/probe_tunnel.py, docs/PERF.md) shows the
# host↔device link rewards FEW, LARGE transfers: bandwidth rises from
# ~6 MB/s at 1 MB to ~24 MB/s at 64 MB, concurrent streams do NOT
# aggregate, and transfers DO overlap device compute. So the hot path
# ships ONE u8 record matrix per chunk — [sig ‖ digest ‖ flags ‖ kid]
# rows — and runs unpack + limb building + expected-EM construction +
# modexp + compare as ONE jitted program returning a [N] bool that is
# only materialized in the batch-wide sync wave.

RS_REC_EXTRA = 2          # trailing bytes per record: flags, key row


def rs_packed_records(table: RSAKeyTable, sig_mat: np.ndarray,
                      sig_lens: np.ndarray, hash_mat: np.ndarray,
                      hash_name: str, key_idx: np.ndarray) -> np.ndarray:
    """Host: build the packed [N, 2k + hlen + 2] u8 record matrix.

    Row layout: right-aligned signature bytes (2k) ‖ digest (hlen) ‖
    validity flag u8 ‖ key row u8. Invalid-length signatures are zeroed
    with flag 0 (their verdict is decided host-side, matching the CPU
    oracle's rejections).
    """
    sizes = np.asarray(table.sizes_bytes, np.int64)[key_idx]
    len_ok = sig_lens == sizes
    em_len_ok = sizes >= len(DIGEST_INFO_PREFIX[hash_name]) + \
        HASH_LEN[hash_name] + 11
    flags = (len_ok & em_len_ok).astype(np.uint8)
    safe_lens = np.where(len_ok, sig_lens, 0)
    width = 2 * table.k
    aligned = L.right_align_bytes(
        np.where(len_ok[:, None], sig_mat[:, :width], 0), safe_lens, width)
    h_len = HASH_LEN[hash_name]
    rec = np.empty((sig_mat.shape[0], width + h_len + RS_REC_EXTRA),
                   np.uint8)
    rec[:, :width] = aligned
    rec[:, width:width + h_len] = hash_mat[:, :h_len]
    rec[:, width + h_len] = flags
    rec[:, width + h_len + 1] = key_idx.astype(np.uint8)
    return rec


def _rs_packed_unpack(packed, k: int, h_len: int):
    """In-jit: record matrix → (s_limbs, dig, flags, idx)."""
    import jax.numpy as jnp

    width = 2 * k
    s_limbs = bytes_to_limbs_device(packed[:, :width])
    dig = packed[:, width:width + h_len]
    flags = packed[:, width + h_len] != 0
    idx = packed[:, width + h_len + 1].astype(jnp.int32)
    return s_limbs, dig, flags, idx


def _rs_packed_rns_impl(packed, sizes_tab, n_tab, sig_c_tab, n_B_tab,
                        a2_A_tab, a2_B_tab, *, k: int, hash_name: str,
                        ctx):
    import jax.numpy as jnp

    from . import bignum
    from .rns import _rns_verify_core

    s_limbs, dig, flags, idx = _rs_packed_unpack(packed, k,
                                                 HASH_LEN[hash_name])
    sizes = sizes_tab[idx]
    expected = _expected_em_device(dig, sizes, k, hash_name)
    in_range = ~bignum.compare_ge(s_limbs, n_tab[idx].T)
    ok = _rns_verify_core(ctx, s_limbs, expected, sig_c_tab[idx].T,
                          n_B_tab[idx].T, a2_A_tab[idx].T,
                          a2_B_tab[idx].T)
    return ok & in_range & flags


def _rs_packed_limb_impl(packed, sizes_tab, n_tab, np_tab, r2_tab,
                         one_tab, e_tab, *, k: int, hash_name: str,
                         ebits: int, all_f4: bool):
    import jax.numpy as jnp

    from . import bignum

    s_limbs, dig, flags, idx = _rs_packed_unpack(packed, k,
                                                 HASH_LEN[hash_name])
    sizes = sizes_tab[idx]
    expected = _expected_em_device(dig, sizes, k, hash_name)
    n = n_tab[idx].T
    in_range = ~bignum.compare_ge(s_limbs, n)
    nprime = np_tab[idx].T
    r2 = r2_tab[idx].T
    if all_f4:
        em = bignum.modexp_65537(s_limbs, n, nprime, r2)
    else:
        em = bignum.modexp_vare(s_limbs, e_tab[idx], n, nprime, r2,
                                one_tab[idx].T, ebits=ebits)
    eq = jnp.all(em == expected, axis=0)
    return eq & in_range & flags


def _ps_packed_rns_impl(packed, mod_bits_tab, n_tab, sig_c_tab, n_B_tab,
                        a2_A_tab, a2_B_tab, *, k: int, hash_name: str,
                        ctx):
    from . import bignum
    from .rns import _rns_modexp_em_core

    s_limbs, dig, flags, idx = _rs_packed_unpack(packed, k,
                                                 HASH_LEN[hash_name])
    n_g = n_tab[idx].T
    in_range = ~bignum.compare_ge(s_limbs, n_g)
    em = _rns_modexp_em_core(ctx, k + 1, s_limbs, sig_c_tab[idx].T,
                             n_B_tab[idx].T, a2_A_tab[idx].T,
                             a2_B_tab[idx].T, n_g)
    em_bytes = _limbs_to_bytes_impl(em[:k])   # canonical < n < 2^16k
    ok = _pss_verify_device(em_bytes, dig, mod_bits_tab[idx],
                            width=2 * k, hash_name=hash_name)
    return ok & in_range & flags


def _ps_packed_limb_impl(packed, mod_bits_tab, n_tab, np_tab, r2_tab,
                         one_tab, e_tab, *, k: int, hash_name: str,
                         ebits: int, all_f4: bool):
    from . import bignum

    s_limbs, dig, flags, idx = _rs_packed_unpack(packed, k,
                                                 HASH_LEN[hash_name])
    n = n_tab[idx].T
    in_range = ~bignum.compare_ge(s_limbs, n)
    nprime = np_tab[idx].T
    r2 = r2_tab[idx].T
    if all_f4:
        em = bignum.modexp_65537(s_limbs, n, nprime, r2)
    else:
        em = bignum.modexp_vare(s_limbs, e_tab[idx], n, nprime, r2,
                                one_tab[idx].T, ebits=ebits)
    em_bytes = _limbs_to_bytes_impl(em)
    ok = _pss_verify_device(em_bytes, dig, mod_bits_tab[idx],
                            width=2 * k, hash_name=hash_name)
    return ok & in_range & flags


_rs_packed_jits: dict = {}


def _rs_packed_jit(name: str, impl, static_names):
    fn = _rs_packed_jits.get(name)
    if fn is None:
        import jax

        fn = jax.jit(impl, static_argnames=static_names)
        _rs_packed_jits[name] = fn
    return fn


def _place_packed(rec: np.ndarray, mesh):
    """Shared mesh/single-device placement for packed dispatches:
    returns (device record, place(table) fn)."""
    import jax

    if mesh is not None:
        from ..parallel.place import replicated, shard_batch

        return shard_batch(mesh, rec), (lambda a: replicated(mesh, a))
    return jax.device_put(rec), (lambda a: a)


def verify_rs_packed_pending(table: RSAKeyTable, rec: np.ndarray,
                             hash_name: str, mesh=None):
    """Dispatch one packed RS* chunk; returns the device [N] bool.

    One H2D transfer (the record matrix), one compiled program, no
    materialization — the caller syncs the whole batch at once. With a
    mesh, the record shards along the batch axis and the tables
    replicate (GSPMD partitions the program — SURVEY.md §2.6).
    """
    dev, place = _place_packed(rec, mesh)
    if table.all_f4 and _use_rns():
        ctx, rtab = table.rns()
        if ctx is not None:
            fn = _rs_packed_jit("rns", _rs_packed_rns_impl,
                                ("k", "hash_name", "ctx"))
            return fn(dev, place(table.sizes_dev), place(table.n_tab),
                      place(rtab.sig_c), place(rtab.n_B),
                      place(rtab.a2_A), place(rtab.a2_B), k=table.k,
                      hash_name=hash_name, ctx=ctx)
    fn = _rs_packed_jit("limb", _rs_packed_limb_impl,
                        ("k", "hash_name", "ebits", "all_f4"))
    return fn(dev, place(table.sizes_dev), place(table.n_tab),
              place(table.np_tab), place(table.r2_tab),
              place(table.one_tab), place(table.e_dev), k=table.k,
              hash_name=hash_name, ebits=table.max_ebits,
              all_f4=table.all_f4)


def verify_ps_packed_pending(table: RSAKeyTable, rec: np.ndarray,
                             hash_name: str, mesh=None):
    """Dispatch one packed PS* chunk; returns the device [N] bool.

    Like verify_rs_packed_pending, but the expected-EM compare is
    replaced by the FULL device-side EMSA-PSS-VERIFY — modexp, MGF1,
    separator scan, and H' hashing all stay on device, so the EM bytes
    (as large as the signature upload) never cross back to the host.
    All three hash families (tpu/sha256.py, tpu/sha512.py).
    """
    dev, place = _place_packed(rec, mesh)
    if table.all_f4 and _use_rns():
        ctx, rtab = table.rns()
        if ctx is not None:
            fn = _rs_packed_jit("ps_rns", _ps_packed_rns_impl,
                                ("k", "hash_name", "ctx"))
            return fn(dev, place(table.mod_bits_dev),
                      place(table.n_tab), place(rtab.sig_c),
                      place(rtab.n_B), place(rtab.a2_A),
                      place(rtab.a2_B), k=table.k,
                      hash_name=hash_name, ctx=ctx)
    fn = _rs_packed_jit("ps_limb", _ps_packed_limb_impl,
                        ("k", "hash_name", "ebits", "all_f4"))
    return fn(dev, place(table.mod_bits_dev), place(table.n_tab),
              place(table.np_tab), place(table.r2_tab),
              place(table.one_tab), place(table.e_dev), k=table.k,
              hash_name=hash_name, ebits=table.max_ebits,
              all_f4=table.all_f4)
