"""RSA modexp in residue number system form — the MXU engine.

The limb engine (``bignum``) is VPU-bound: per-token convolutions
can't use the systolic array because both operands vary per token.
This module restructures modexp so the heavy lifting IS a matmul:

- numbers live as residues modulo two bases of ~13-bit primes
  (A, B with prod(A) ≥ 16·n): multiplication and squaring become
  ELEMENTWISE per-channel products (VPU, cheap);
- Montgomery reduction (Bajard/Kawamura RNS-REDC) needs two base
  extensions per step, and a base extension is a matrix product
  against a FIXED [I, I] matrix of precomputed residues — shared by
  every token and every key, so the whole batch rides the MXU;
- exactness on a bf16/f32 MXU: every 13-bit operand is split into
  7-bit halves, giving four bf16 matmuls whose f32 accumulations stay
  below 2^24 (integer-exact); channel reductions use Barrett
  guess-then-fix (f32 picks the quotient to within ±1, i32 computes
  the exact remainder, one conditional correction each way);
- the A→B extension runs with floor-approximated α (error ∈ {-1, 0} —
  a bounded extra multiple of A that the value bound absorbs); the
  B→A extension adds the Kawamura 0.5 offset, which is EXACT here
  because t ≪ B/2; the chain keeps every value < 3n without a single
  comparison;
- no RNS→binary conversion at the end: the PKCS#1 v1.5 check compares
  the result against RNS(expected_EM + c·n) for c ∈ {0, 1, 2} in base
  B — equality of all residues is exact equality below prod(B).

Replaces crypto/rsa.VerifyPKCS1v15's modexp (the reference's hot loop,
jwt/keyset.go:126-139 → go-jose → Go stdlib) for e = 65537 keys.
Validated bit-for-bit against the prototype in tools/rns_proto.py and
the CPU oracle in tests.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

I32 = jnp.int32
F32 = jnp.float32
BF16 = jnp.bfloat16


# ---------------------------------------------------------------------------
# Host-side base construction
# ---------------------------------------------------------------------------

def _sieve_primes(lo: int, hi: int):
    mask = np.ones(hi, bool)
    mask[:2] = False
    for i in range(2, int(hi ** 0.5) + 1):
        if mask[i]:
            mask[i * i:: i] = False
    return [p for p in range(lo, hi) if mask[p]]


class _Base:
    """One RNS base: moduli + CRT reconstruction constants."""

    def __init__(self, ms):
        self.m = np.asarray(ms, np.int64)
        self.count = len(ms)
        self.prod = 1
        for p in ms:
            self.prod *= int(p)
        self.Mi = [self.prod // int(p) for p in ms]
        self.inv_Mi = np.asarray(
            [pow(M % int(p), -1, int(p)) for M, p in zip(self.Mi, self.m)],
            np.int64)


def _ext_matrix(src: _Base, dst: _Base) -> np.ndarray:
    w = np.empty((dst.count, src.count), np.int64)
    for i, mi in enumerate(src.Mi):
        w[:, i] = np.asarray([mi % int(m) for m in dst.m], np.int64)
    return w


class RNSContext:
    """Per-bit-width device context: bases, extension + conversion mats.

    Key-independent; cached per (nbits). ``nbits`` is the max modulus
    bit length the context must support (prod(A) ≥ 2^(nbits+4) ≥ 16n).
    """

    def __init__(self, nbits: int, k_limbs: int):
        # Primes in [2^12, 2^14): ~1330 of them — enough for ~8k-bit
        # moduli. 14-bit values keep every exactness bound: 7-bit split
        # halves < 2^7, f32 matmul sums < 2^24, Barrett inputs < 2^31.
        primes = _sieve_primes(1 << 12, 1 << 14)
        # Deterministic order → deterministic contexts.
        need = nbits + 8
        msA, bits, i = [], 0.0, 0
        try:
            while bits < need:
                msA.append(primes[i])
                bits += np.log2(primes[i])
                i += 1
            msB, bits = [], 0.0
            while bits < need:
                msB.append(primes[i])
                bits += np.log2(primes[i])
                i += 1
        except IndexError:
            raise RNSUnsupportedKey(
                f"modulus width {nbits} exceeds the RNS prime pool")
        self.A = _Base(msA)
        self.B = _Base(msB)
        self.nbits = nbits
        self.k_limbs = k_limbs

        def dev_base(base: _Base):
            return dict(
                m=jnp.asarray(base.m, I32),
                m_f=jnp.asarray(base.m, F32),
                inv_f=jnp.asarray(1.0 / base.m, F32),
                inv_Mi=jnp.asarray(base.inv_Mi, I32),
            )

        self.dA = dev_base(self.A)
        self.dB = dev_base(self.B)
        self.W_AB = _split_mat(_ext_matrix(self.A, self.B))
        self.W_BA = _split_mat(_ext_matrix(self.B, self.A))
        self.Amod_B = jnp.asarray(
            [self.A.prod % int(m) for m in self.B.m], I32)
        self.Bmod_A = jnp.asarray(
            [self.B.prod % int(m) for m in self.A.m], I32)
        self.invA_B = jnp.asarray(
            [pow(self.A.prod % int(m), -1, int(m)) for m in self.B.m], I32)

        # limb→RNS conversion: T[c, l] = 2^(16l) mod m_c for each base.
        def conv_mat(base: _Base):
            t = np.empty((base.count, k_limbs), np.int64)
            for ll in range(k_limbs):
                t[:, ll] = np.asarray(
                    [pow(2, 16 * ll, int(m)) for m in base.m], np.int64)
            return _split_mat(t)

        self.T_A = conv_mat(self.A)
        self.T_B = conv_mat(self.B)


_CTX_CACHE: Dict[Tuple[int, int], RNSContext] = {}


def context(nbits: int, k_limbs: int) -> RNSContext:
    key = (nbits, k_limbs)
    if key not in _CTX_CACHE:
        _CTX_CACHE[key] = RNSContext(nbits, k_limbs)
    return _CTX_CACHE[key]


def _split_mat(w: np.ndarray):
    """13-bit int matrix → (hi, lo) bf16 halves (7-bit exact)."""
    return (jnp.asarray(w >> 7, BF16), jnp.asarray(w & 127, BF16))


def use_rns() -> bool:
    """RNS/MXU engines on accelerators; limb/VPU path elsewhere.

    Override with CAP_TPU_RNS=1/0 (tests force 1 on CPU to pin RNS
    parity; CPU default stays on the limb path, which compiles much
    faster there).
    """
    import os

    v = os.environ.get("CAP_TPU_RNS")
    if v is not None:
        return v not in ("0", "false", "no")
    return jax.default_backend() not in ("cpu",)


class RNSUnsupportedKey(ValueError):
    """A modulus shares a factor with an RNS base prime (or is even).

    Impossible for well-formed RSA keys (n = p·q with large primes);
    raised for degenerate/garbage keys so callers fall back to the
    limb engine, preserving bit-exact parity even for invalid keys.
    """


class RNSKeyTable:
    """Per-key RNS constants, gathered per token (the key-gather axis).

    For each key: n in both bases, the merged σ constant
    (-n⁻¹·(A/a_i)⁻¹ mod a_i), and A² mod n in both bases (domain
    entry).
    """

    def __init__(self, ctx: RNSContext, n_ints: Sequence[int]):
        self.ctx = ctx
        nk = len(n_ints)
        a = ctx.A
        b = ctx.B
        n_B = np.empty((nk, b.count), np.int64)
        sig_c = np.empty((nk, a.count), np.int64)
        a2_A = np.empty((nk, a.count), np.int64)
        a2_B = np.empty((nk, b.count), np.int64)
        for j, n in enumerate(n_ints):
            if n <= 0 or n % 2 == 0:
                raise RNSUnsupportedKey(f"modulus of key {j} is not odd")
            a2n = (a.prod * a.prod) % n
            for i, m in enumerate(a.m):
                m = int(m)
                try:
                    npr = (-pow(n, -1, m)) % m
                except ValueError as e:
                    raise RNSUnsupportedKey(
                        f"modulus of key {j} shares a factor with an RNS "
                        f"base prime") from e
                sig_c[j, i] = (npr * int(a.inv_Mi[i])) % m
                a2_A[j, i] = a2n % m
            for i, m in enumerate(b.m):
                m = int(m)
                n_B[j, i] = n % m
                a2_B[j, i] = a2n % m
        self.n_B = jnp.asarray(n_B, I32)
        self.sig_c = jnp.asarray(sig_c, I32)
        self.a2_A = jnp.asarray(a2_A, I32)
        self.a2_B = jnp.asarray(a2_B, I32)


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------

def _mod_fix(x: jnp.ndarray, m: jnp.ndarray,
             inv_f: jnp.ndarray) -> jnp.ndarray:
    """Exact x mod m for 0 ≤ x < 2^31: f32 Barrett guess, i32 fix.

    One correction each way: the f32 quotient guess is within ±1 of
    floor(x/m) — |f32(x) − x| ≤ ulp(2^31)/2 = 128 contributes
    ≤ 128/m ≤ 2^-5 (m ≥ 2^12), the 1/m constant's rounding
    ≤ (x/m)·2^-24 ≤ 2^-5, the product's rounding ≤ ulp(2^19)/2
    = 2^-5 — total ≤ 0.094 < 1, so r = x − q·m ∈ (−m, 2m).
    """
    q = jnp.floor(x.astype(F32) * inv_f).astype(I32)
    r = x - q * m
    r = jnp.where(r < 0, r + m, r)
    r = jnp.where(r >= m, r - m, r)
    return r


def _split_matmul(w_pair, x: jnp.ndarray):
    """Σ W·x via ONE exact bf16 matmul → (hh, mid, ll) f32→i32.

    w_pair: (Wh, Wl) bf16 [J, I] 7-bit halves; x: [I, N] i32 < 2^14.

    Two layouts, chosen by context width:
    - SMALL contexts (every EC/Ed field: 3J ≤ 128 and 2I ≤ 128): the
      hi/lo split rides the M and K axes via the shared block matrix
      ``[[Wh,0],[0,Wl],[Wl,Wh]]`` [3J, 2I] (pallas_redc._w_block —
      ONE encoder for kernel and XLA paths) times ``[x>>7 ; x&127]``
      [2I, N]. N halves while M and K stay inside one 128-lane MXU
      block, so the unit count halves outright. The one-dot mid
      accumulation is ≤ 2I·127² — f32-exact through I ≤ 520, amply
      guarded by the 2I ≤ 128 gate.
    - WIDE contexts (RSA: I ≈ nbits/12, hundreds of channels): keep
      the [2J, I] @ [I, 2N] quadrant layout. The block form's M/K are
      already multi-block there, so it pads ~1.5× MORE MXU work, and
      its single-dot mid would overflow f32 past I = 520. Quadrant
      mids accumulate only I ≤ 1040 terms (asserted), covering every
      modulus the prime pool itself can support.
    Row groups either way: hh (weight 2^14 via c14), mid (2^7), ll.
    """
    wh, wl = w_pair
    j, i = wh.shape
    if 3 * j <= 128 and 2 * i <= 128:
        from .pallas_redc import _w_block

        w_blk = jnp.asarray(_w_block((wh, wl)))          # [3J, 2I]
        x_blk = jnp.concatenate(
            [(x >> 7).astype(BF16), (x & 127).astype(BF16)], axis=0)
        c = jnp.dot(w_blk, x_blk,
                    preferred_element_type=F32).astype(I32)
        return c[:j], c[2 * j:], c[j:2 * j]
    assert i <= 1040, "quadrant mid accumulation would overflow f32"
    n = x.shape[1]
    w_cat = jnp.concatenate([wh, wl], axis=0)            # [2J, I]
    x_cat = jnp.concatenate(
        [(x >> 7).astype(BF16), (x & 127).astype(BF16)], axis=1)
    c = jnp.dot(w_cat, x_cat, preferred_element_type=F32).astype(I32)
    hh = c[:j, :n]
    mid = c[:j, n:] + c[j:, :n]
    ll = c[j:, n:]
    return hh, mid, ll


def _extend(sig: jnp.ndarray, src_dev, dst_dev, w_pair,
            src_prod_mod_dst: jnp.ndarray, offset: float) -> jnp.ndarray:
    """Base extension of σ rows: [I_src, N] → [I_dst, N].

    offset: -1e-4 for the A→B direction (α error ∈ {-1, 0}, absorbed
    by the value bound); 0.5-1e-4 for B→A (exact α: t ≪ B/2).
    """
    hh, mid, ll = _split_matmul(w_pair, sig)
    alpha = jnp.floor(
        jnp.sum(sig.astype(F32) * src_dev["inv_f"][:, None], axis=0)
        + offset).astype(I32)                       # [N]
    m = dst_dev["m"][:, None]
    inv_f = dst_dev["inv_f"][:, None]

    def fix(v):
        return _mod_fix(v, m, inv_f)

    c14 = (1 << 14) % m
    i_src = sig.shape[0]
    if i_src <= 448:
        # 2^7 mod m = 128 EXACTLY (m ≥ 2^12), so mid/ll skip their
        # per-term fixes: fix(hh)·c14 < 2^28, mid·128 ≤ 2I·127²·128,
        # ll ≤ I·127² — the sum stays < 2^31 for I ≤ 448 (covers
        # every context through 4096-bit moduli).
        comb = fix(fix(hh) * c14 + mid * 128 + ll)
    else:
        comb = fix(fix(hh) * c14 + fix(mid) * 128 + fix(ll))
    # α ∈ [-1, I_src]: only the -1 case (floor undershoot at q ≈ 0)
    # needs the modular wrap — a select, not an integer division.
    alpha_adj = jnp.where(alpha < 0, alpha[None, :] + m,
                          alpha[None, :])
    corr = fix(alpha_adj * src_prod_mod_dst[:, None])
    # comb, corr < m → comb − corr + m ∈ (0, 2m): one conditional
    # subtract replaces the full Barrett pass (identical result).
    r = comb - corr + m
    return jnp.where(r >= m, r - m, r)


def _redc(x_A, x_B, sig_c, n_B, ctx_consts):
    """One RNS Montgomery reduction: x → x·A⁻¹ mod n (value < 3n)."""
    (dA, dB, W_AB, W_BA, Amod_B, Bmod_A, invA_B) = ctx_consts
    mA, invA_f = dA["m"][:, None], dA["inv_f"][:, None]
    mB, invB_f = dB["m"][:, None], dB["inv_f"][:, None]

    sig = _mod_fix(x_A * sig_c, mA, invA_f)
    q_B = _extend(sig, dA, dB, W_AB, Amod_B, offset=-1e-4)
    # q·n + x < 2^28: one fix covers the merged product-and-add
    t_B = _mod_fix(x_B + q_B * n_B, mB, invB_f)
    t_B = _mod_fix(t_B * invA_B[:, None], mB, invB_f)
    sig2 = _mod_fix(t_B * dB["inv_Mi"][:, None], mB, invB_f)
    t_A = _extend(sig2, dB, dA, W_BA, Bmod_A, offset=0.5 - 1e-4)
    return t_A, t_B


def _mul_redc(aA, aB, bA, bB, sig_c, n_B, ctx_consts, dA, dB):
    pA = _mod_fix(aA * bA, dA["m"][:, None], dA["inv_f"][:, None])
    pB = _mod_fix(aB * bB, dB["m"][:, None], dB["inv_f"][:, None])
    return _redc(pA, pB, sig_c, n_B, ctx_consts)


def _limbs_to_rns(limbs: jnp.ndarray, t_pair, dev) -> jnp.ndarray:
    """[K, N] u32 16-bit limbs → [I, N] i32 residues.

    Conversion is a fixed matmul over 8-bit limb halves: residues
    = Σ_l (2^(16l) mod m)·limb_l, split 7×8 bits for f32 exactness.
    """
    th, tl = t_pair
    lh = (limbs >> 8).astype(BF16)
    ll = (limbs & 0xFF).astype(BF16)

    def mm(a, b):
        return jnp.dot(a, b, preferred_element_type=F32).astype(I32)

    hh = mm(th, lh)      # weight 2^15
    hl = mm(th, ll)      # weight 2^7
    lh2 = mm(tl, lh)     # weight 2^8
    ll2 = mm(tl, ll)     # weight 2^0
    m = dev["m"][:, None]
    inv_f = dev["inv_f"][:, None]

    def fix(v):
        return _mod_fix(v, m, inv_f)

    c15 = (1 << 15) % m
    c8 = (1 << 8) % m
    c7 = (1 << 7) % m
    return fix(fix(hh) * c15 + fix(fix(hl) * c7 + fix(lh2) * c8)
               + fix(ll2))


class FieldRNSContext:
    """Shared RNS context for a fixed prime field (EC / Edwards engines).

    Two bases of 13-bit primes (the lazy fix-free adds/subs in the
    point ladders require m < 2^13 so digit-growth products stay below
    2^31), extension + conversion matrices, the merged σ constant for
    REDC, c·p residue rows for congruence tests/positive subtracts,
    the A-domain entry constant A² mod p, and a CRT reconstructor.
    """

    def __init__(self, p: int, k_limbs: int, slack_bits: int = 16,
                 maxc: int = 32):
        self.p_int = p
        primes = _sieve_primes(1 << 12, 1 << 13)
        need = p.bit_length() + slack_bits
        msA, bits, i = [], 0.0, 0
        while bits < need:
            msA.append(primes[i])
            bits += np.log2(primes[i])
            i += 1
        msB, bits = [], 0.0
        while bits < need:
            msB.append(primes[i])
            bits += np.log2(primes[i])
            i += 1
        self.A = _Base(msA)
        self.B = _Base(msB)

        def dev_base(base: _Base):
            return dict(
                m=jnp.asarray(base.m, I32),
                m_f=jnp.asarray(base.m, F32),
                inv_f=jnp.asarray(1.0 / base.m, F32),
                inv_Mi=jnp.asarray(base.inv_Mi, I32),
            )

        self.dA = dev_base(self.A)
        self.dB = dev_base(self.B)
        self.W_AB = _split_mat(_ext_matrix(self.A, self.B))
        self.W_BA = _split_mat(_ext_matrix(self.B, self.A))
        self.Amod_B = jnp.asarray(
            [self.A.prod % int(m) for m in self.B.m], I32)
        self.Bmod_A = jnp.asarray(
            [self.B.prod % int(m) for m in self.A.m], I32)
        self.invA_B = jnp.asarray(
            [pow(self.A.prod % int(m), -1, int(m)) for m in self.B.m],
            I32)
        ppr = [(-pow(p, -1, int(m))) % int(m) for m in self.A.m]
        self.sig_c = jnp.asarray(
            [(v * int(inv)) % int(m) for v, inv, m in
             zip(ppr, self.A.inv_Mi, self.A.m)], I32)[:, None]
        self.p_B = jnp.asarray([p % int(m) for m in self.B.m],
                               I32)[:, None]
        self.cp_A = jnp.asarray(
            [[(c * p) % int(m) for m in self.A.m] for c in range(maxc)],
            I32)
        self.cp_B = jnp.asarray(
            [[(c * p) % int(m) for m in self.B.m] for c in range(maxc)],
            I32)
        self.consts = (self.dA, self.dB, self.W_AB, self.W_BA,
                       self.Amod_B, self.Bmod_A, self.invA_B)
        self.a_mod_p = self.A.prod % p
        a2 = (self.A.prod * self.A.prod) % p
        self.A2 = (jnp.asarray([a2 % int(m) for m in self.A.m],
                               I32)[:, None],
                   jnp.asarray([a2 % int(m) for m in self.B.m],
                               I32)[:, None])

        def conv_mat(base: _Base):
            t = np.empty((base.count, k_limbs), np.int64)
            for ll in range(k_limbs):
                t[:, ll] = np.asarray(
                    [pow(2, 16 * ll, int(m)) for m in base.m], np.int64)
            return _split_mat(t)

        self.T_A = conv_mat(self.A)
        self.T_B = conv_mat(self.B)
        self.to_limbs = RNSToLimbs(self.A, k_limbs + 1)

    def residues_of(self, x: int) -> np.ndarray:
        """Plain host int → concatenated [I_A + I_B] residue row."""
        return np.asarray(
            [x % int(m) for m in self.A.m]
            + [x % int(m) for m in self.B.m], np.int64)


class RNSToLimbs:
    """Device CRT reconstruction: base-A residues → 16-bit limb arrays.

    value = Σ_i σ_i·(A/a_i) − α·A with σ_i = x_i·(A/a_i)⁻¹ mod a_i and
    α = ⌊Σ σ_i/a_i⌉ (exact via the +0.5 offset — values are ≪ A). The
    Σ is a fixed matmul against the limb rows of A/a_i, split 7+7 / 8+8
    bits for f32 exactness, with every weighted part scattered across
    two adjacent limbs so u32 accumulators never overflow.

    Valid for values < max_c·p ≪ A (the engines' tracked bounds).
    """

    def __init__(self, base: _Base, k_out: int):
        # Instances are cached (_TO_LIMBS_CACHE) and may be built
        # lazily during a jit trace; without the compile-time-eval
        # guard the jnp constants below would be TRACERS of that trace
        # and poison every later call (UnexpectedTracerError).
        import jax

        with jax.ensure_compile_time_eval():
            self._init(base, k_out)

    def _init(self, base: "_Base", k_out: int):
        self.base = base
        self.k_out = k_out
        bits = int(np.ceil(np.log2(float(base.count)))) + \
            sum(int(m).bit_length() for m in base.m)
        self.k2 = (bits + 15) // 16 + 1
        t16 = np.empty((self.k2, base.count), np.int64)
        for i, mi in enumerate(base.Mi):
            v = mi
            for ll in range(self.k2):
                t16[ll, i] = v & 0xFFFF
                v >>= 16
        # 8-bit halves of the limb rows, as bf16 [K2, I]
        self.t_hi = jnp.asarray(t16 >> 8, BF16)
        self.t_lo = jnp.asarray(t16 & 0xFF, BF16)
        a_limbs = np.zeros(self.k2, np.uint32)
        v = base.prod
        for ll in range(self.k2):
            a_limbs[ll] = v & 0xFFFF
            v >>= 16
        self.a_limbs = jnp.asarray(a_limbs)
        self.inv_f = jnp.asarray(1.0 / base.m, F32)
        self.inv_Mi = jnp.asarray(base.inv_Mi, I32)
        self.m = jnp.asarray(base.m, I32)
        self.m_f = jnp.asarray(base.m, F32)
        self.minv_f = jnp.asarray(1.0 / base.m, F32)

    def __call__(self, x_a: jnp.ndarray) -> jnp.ndarray:
        """[I, N] base-A residues → [k_out, N] u32 limbs of the value."""
        from . import bignum as B

        sig = _mod_fix(x_a * self.inv_Mi[:, None], self.m[:, None],
                       self.minv_f[:, None])
        alpha = jnp.floor(
            jnp.sum(sig.astype(F32) * self.inv_f[:, None], axis=0)
            + 0.5).astype(I32)                        # exact: value ≪ A

        sh = (sig >> 7).astype(BF16)
        sl = (sig & 127).astype(BF16)

        def mm(a, b):
            return jnp.dot(a, b, preferred_element_type=F32).astype(
                jnp.uint32)

        hh = mm(self.t_hi, sh)     # weight 2^15
        hl = mm(self.t_hi, sl)     # weight 2^8
        lh = mm(self.t_lo, sh)     # weight 2^7
        ll = mm(self.t_lo, sl)     # weight 2^0

        def spread(v, shift):
            # v·2^shift at limb l → low bits at l, high bits at l+1
            lo = (v << shift) & 0xFFFF
            hi = v >> (16 - shift)
            return lo + jnp.concatenate(
                [jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)

        acc = (spread(hh, 15) + spread(hl, 8) + spread(lh, 7) + ll)
        acc = B.carry_normalize(
            jnp.pad(acc, ((0, 1), (0, 0))))           # [K2+1, N]
        corr = B.carry_normalize(
            alpha[None, :].astype(jnp.uint32)
            * jnp.pad(self.a_limbs, (0, 1))[:, None])
        out = B.sub_where(acc, corr,
                          jnp.ones(acc.shape[1], dtype=bool))
        return out[: self.k_out]


@partial(jax.jit, static_argnums=(0,))
def _rns_verify_core(ctx: RNSContext, s_limbs, expected_limbs,
                     sig_c, n_B, a2_A, a2_B):
    """Batched s^65537 mod n == expected (+c·n) check, all in RNS.

    s_limbs/expected_limbs: [K, N] u32; remaining: [I, N] gathered
    per-token key constants. Returns ok [N] bool.
    """
    dA, dB = ctx.dA, ctx.dB
    consts = (dA, dB, ctx.W_AB, ctx.W_BA, ctx.Amod_B, ctx.Bmod_A,
              ctx.invA_B)

    sA = _limbs_to_rns(s_limbs, ctx.T_A, dA)
    sB = _limbs_to_rns(s_limbs, ctx.T_B, dB)
    xA, xB = _mul_redc(sA, sB, a2_A, a2_B, sig_c, n_B, consts, dA, dB)
    x0A, x0B = xA, xB
    for _ in range(16):
        xA, xB = _mul_redc(xA, xB, xA, xB, sig_c, n_B, consts, dA, dB)
    xA, xB = _mul_redc(xA, xB, x0A, x0B, sig_c, n_B, consts, dA, dB)
    # exit the Montgomery domain: multiply by 1 and reduce
    xA, xB = _redc(xA, xB, sig_c, n_B, consts)

    eB = _limbs_to_rns(expected_limbs, ctx.T_B, dB)
    mB = dB["m"][:, None]
    invB_f = dB["inv_f"][:, None]
    ok = jnp.zeros(s_limbs.shape[1], bool)
    shifted = eB
    for _ in range(3):                      # c = 0, 1, 2 (result < 3n)
        ok = ok | jnp.all(xB == shifted, axis=0)
        shifted = _mod_fix(shifted + n_B, mB, invB_f)
    return ok


@partial(jax.jit, static_argnums=(0, 1))
def _rns_modexp_em_core(ctx: RNSContext, k_out: int, s_limbs,
                        sig_c, n_B, a2_A, a2_B, n_limbs):
    """s^65537 mod n as LIMBS (for host-side EM checks, e.g. PSS).

    Same RNS chain as the verify core, then CRT reconstruction back to
    limbs and canonicalization below the per-token modulus.
    """
    from . import bignum as B

    dA, dB = ctx.dA, ctx.dB
    consts = (dA, dB, ctx.W_AB, ctx.W_BA, ctx.Amod_B, ctx.Bmod_A,
              ctx.invA_B)
    sA = _limbs_to_rns(s_limbs, ctx.T_A, dA)
    sB = _limbs_to_rns(s_limbs, ctx.T_B, dB)
    xA, xB = _mul_redc(sA, sB, a2_A, a2_B, sig_c, n_B, consts, dA, dB)
    x0A, x0B = xA, xB
    for _ in range(16):
        xA, xB = _mul_redc(xA, xB, xA, xB, sig_c, n_B, consts, dA, dB)
    xA, xB = _mul_redc(xA, xB, x0A, x0B, sig_c, n_B, consts, dA, dB)
    xA, xB = _redc(xA, xB, sig_c, n_B, consts)   # exit domain; < 3n

    conv = _to_limbs_for(ctx, k_out)
    v = conv(xA)                                  # [k_out, N]
    n_pad = jnp.concatenate(
        [n_limbs, jnp.zeros_like(n_limbs[:1])], axis=0)
    for _ in range(2):
        v = B.sub_where(v, n_pad, B.compare_ge(v, n_pad))
    return v[: n_limbs.shape[0]]


_TO_LIMBS_CACHE: Dict[Tuple[int, int], "RNSToLimbs"] = {}


def _to_limbs_for(ctx: RNSContext, k_out: int) -> "RNSToLimbs":
    key = (id(ctx), k_out)
    if key not in _TO_LIMBS_CACHE:
        _TO_LIMBS_CACHE[key] = RNSToLimbs(ctx.A, k_out)
    return _TO_LIMBS_CACHE[key]


def modexp_em_device(ctx: RNSContext, table: RNSKeyTable,
                     s_limbs, key_idx: np.ndarray,
                     n_limbs_gathered) -> jnp.ndarray:
    """Async device [K, N] limbs of s^65537 mod n (PSS path)."""
    idx = jnp.asarray(key_idx, I32)
    k = s_limbs.shape[0]
    return _rns_modexp_em_core(
        ctx, k + 1, jnp.asarray(s_limbs),
        table.sig_c[idx].T, table.n_B[idx].T,
        table.a2_A[idx].T, table.a2_B[idx].T,
        n_limbs_gathered)


def verify_em_equals_device(ctx: RNSContext, table: RNSKeyTable,
                            s_limbs: np.ndarray,
                            expected_limbs: np.ndarray,
                            key_idx: np.ndarray) -> jnp.ndarray:
    """Async: device [N] bool, s^65537 mod n == expected (e=65537)."""
    idx = jnp.asarray(key_idx, I32)
    return _rns_verify_core(
        ctx, jnp.asarray(s_limbs), jnp.asarray(expected_limbs),
        table.sig_c[idx].T, table.n_B[idx].T,
        table.a2_A[idx].T, table.a2_B[idx].T)


def verify_em_equals(ctx: RNSContext, table: RNSKeyTable,
                     s_limbs: np.ndarray, expected_limbs: np.ndarray,
                     key_idx: np.ndarray) -> np.ndarray:
    """[N] bool: s^65537 mod n == expected, for e=65537 key tables."""
    return np.asarray(verify_em_equals_device(
        ctx, table, s_limbs, expected_limbs, key_idx))
