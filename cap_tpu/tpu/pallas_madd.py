"""Fused RNS mixed-add (Jacobian + affine) as one Pallas TPU kernel.

The ES*/Ed ladders are HBM-bandwidth-bound under XLA: each of the ~5
REDC layers per window materializes its [I, 2N] residue planes to HBM
between kernels, and the measured per-layer cost is ~6× the pure
traffic of one read+write pass (docs/PERF.md round-3 A/Bs — wider
windows and more chains both lost because they scale traffic, not
depth). This kernel runs ec_rns._madd_rns END-TO-END on VMEM tiles —
11 rmuls (each a full Bajard/Kawamura REDC with both base extensions),
the lazy adds/subs, the degeneracy probe, and the infinity/digit-0
selection — touching HBM once for inputs and once for outputs.

Numerical contract: bit-identical to the XLA path (same fixed-point
ops, same lazily-tracked bounds — every product stays < 2^31); parity
pinned by tests/test_pallas_madd.py in interpret mode on CPU and by
the RNS suite on device. Enabled via CAP_TPU_PALLAS_MADD (default ON
for TPU backends once measured faster; A/B in docs/PERF.md).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict

import numpy as np

import jax
import jax.numpy as jnp

from .pallas_redc import _fix, make_rns_ops

I32 = jnp.int32
F32 = jnp.float32

_TILE = int(os.environ.get("CAP_TPU_MADD_TILE", 512))  # lanes/step
_DEG_MAXC = 20      # same-x probe candidates (h < 20p)
_DEG_CH = 2         # probe channels (false-positive ~maxc/m0/m1)


def enabled() -> bool:
    """Fused Pallas mixed-add: CAP_TPU_PALLAS_MADD=1/0 overrides.

    Default ON for accelerator backends: measured 157 -> 140 ms per
    32k-token ES256 core (+11%) at tile 512 (tiles 256/512 tie, 1024
    slightly worse, 2048 catastrophically spills — docs/PERF.md).
    CPU stays on the XLA path (interpret mode is far slower and the
    XLA path is the reference for parity tests).
    """
    v = os.environ.get("CAP_TPU_PALLAS_MADD")
    if v is not None:
        return v not in ("0", "false", "no")
    # Mosaic/TPU kernel only: a GPU backend must keep the XLA path.
    return jax.default_backend() == "tpu"


def _madd_math(X, Y, Z, x2, y2, has, inf,
               mA, mB, sigc, nB, wab, wba,
               amodb, bmoda, invab, invmib, cpA, cpB, oneA, oneB,
               c14a, c14b):
    """One mixed-add step on VALUES (VMEM arrays, not refs).

    Bit-identical to ec_rns._madd_rns + the lift/digit-0 select;
    shared verbatim by the single-window kernel and the fused
    multi-window ladder kernel so their numerics cannot diverge.
    Returns (oxa, oxb, oya, oyb, oza, ozb, deg).
    """
    invA_f = 1.0 / mA.astype(F32)
    _, _, rmul, radd, rsub, rfix = make_rns_ops(
        mA, mB, sigc, nB, wab, wba,
        amodb, bmoda, invab, invmib, cpA, cpB, c14a, c14b)

    # _madd_rns, layer for layer (bounds comments live there).
    z1z1 = rmul(Z, Z)
    u2 = rmul(x2, z1z1)
    z1_3 = rmul(Z, z1z1)
    h = rsub(u2, X, 16, 1)
    zh = radd(Z, h)
    s2 = rmul(y2, z1_3)
    hh = rmul(h, h)
    zh2 = rmul(zh, zh)
    i4 = radd(radd(hh, hh), radd(hh, hh))
    s2y1 = rsub(s2, Y, 16, 1)
    rr = rfix(radd(s2y1, s2y1))
    j = rmul(h, i4)
    v = rmul(X, i4)
    r2_ = rmul(rr, rr)
    vv = radd(v, v)
    X3 = rfix(rsub(rsub(r2_, j, 4, 1), vv, 8, 2))
    y1j = rmul(Y, j)
    t5 = rmul(rr, rsub(v, X3, 16, 1))
    Y3 = rfix(rsub(t5, radd(y1j, y1j), 8, 2))
    Z3 = rfix(rsub(rsub(zh2, z1z1, 4, 1), hh, 4, 1))

    # same-x degeneracy probe on _DEG_CH channels (ec_rns
    # congruent_zero_probe): sufficient, false positives → CPU oracle.
    h_probe = _fix(h[0][:_DEG_CH], mA[:_DEG_CH], invA_f[:_DEG_CH])
    deg = jnp.zeros((1, h_probe.shape[1]), I32)
    for cc in range(_DEG_MAXC):
        cand = cpA[:_DEG_CH, cc:cc + 1]
        hit = jnp.min(
            jnp.where(h_probe == cand, 1, 0), axis=0, keepdims=True)
        deg = deg | hit
    not_inf = 1 - inf
    deg = deg & not_inf & has

    # infinity lift + digit-0 select (ec_rns.add_from_table semantics)
    lift = inf & has

    def pick(res, addend, one_col, orig):
        sel_l = lift != 0
        r = jnp.where(sel_l, addend, res) if one_col is None else \
            jnp.where(sel_l, jnp.broadcast_to(one_col, res.shape), res)
        return jnp.where(has != 0, r, orig)

    return (pick(X3[0], x2[0], None, X[0]),
            pick(X3[1], x2[1], None, X[1]),
            pick(Y3[0], y2[0], None, Y[0]),
            pick(Y3[1], y2[1], None, Y[1]),
            pick(Z3[0], None, oneA, Z[0]),
            pick(Z3[1], None, oneB, Z[1]),
            deg)


def _madd_kernel(xa_ref, xb_ref, ya_ref, yb_ref, za_ref, zb_ref,
                 pxa_ref, pya_ref,
                 has_ref, inf_ref,
                 mA_ref, mB_ref, sigc_ref, nB_ref,
                 wab_ref, wba_ref,
                 amodb_ref, bmoda_ref, invab_ref, invmib_ref,
                 cpA_ref, cpB_ref, oneA_ref, oneB_ref,
                 c14a_ref, c14b_ref,
                 oxa_ref, oxb_ref, oya_ref, oyb_ref, oza_ref, ozb_ref,
                 deg_ref):
    # cpA/cpB are [I, maxc] pre-transposed: static 2-D slices only —
    # int indexing lowers to a gather Mosaic rejects. Table points
    # arrive as packed A|B<<16 words (halved gather traffic,
    # ec_rns._pack_residue_rows) and unpack here on VMEM.
    from .ec_rns import unpack_pt

    ia = xa_ref.shape[0]
    ib = xb_ref.shape[0]
    oxa, oxb, oya, oyb, oza, ozb, deg = _madd_math(
        (xa_ref[:], xb_ref[:]), (ya_ref[:], yb_ref[:]),
        (za_ref[:], zb_ref[:]),
        unpack_pt(pxa_ref[:], ia, ib),
        unpack_pt(pya_ref[:], ia, ib),
        has_ref[:], inf_ref[:],
        mA_ref[:], mB_ref[:], sigc_ref[:], nB_ref[:],
        wab_ref[:], wba_ref[:],
        amodb_ref[:], bmoda_ref[:], invab_ref[:], invmib_ref[:],
        cpA_ref[:], cpB_ref[:], oneA_ref[:], oneB_ref[:],
        c14a_ref[:], c14b_ref[:])
    oxa_ref[:] = oxa
    oxb_ref[:] = oxb
    oya_ref[:] = oya
    oyb_ref[:] = oyb
    oza_ref[:] = oza
    ozb_ref[:] = ozb
    deg_ref[:] = deg


_CONSTS: Dict[int, tuple] = {}


def _ctx_consts(c) -> tuple:
    from .pallas_redc import pinned_ctx_cache

    return pinned_ctx_cache(_CONSTS, c, lambda: _build_consts(c))


def _build_consts(c) -> tuple:
    (dA, dB, w_ab, w_ba, Amod_B, Bmod_A, invA_B) = c.consts

    def col(v):
        # host numpy only: this cache must never hold tracers
        return np.asarray(v, np.int32).reshape(-1, 1)

    a_mod_p = c.A.prod % c.cp.p
    one_a = col([a_mod_p % int(m) for m in c.A.m])
    one_b = col([a_mod_p % int(m) for m in c.B.m])
    from .pallas_redc import _w_block

    return (
        col(dA["m"]), col(dB["m"]), col(c.sig_c), col(c.p_B),
        _w_block(w_ab), _w_block(w_ba),
        col(Amod_B), col(Bmod_A), col(invA_B), col(dB["inv_Mi"]),
        np.ascontiguousarray(np.asarray(c.cp_A, np.int32).T),
        np.ascontiguousarray(np.asarray(c.cp_B, np.int32).T),
        one_a, one_b,
        col((1 << 14) % np.asarray(c.A.m, np.int64)),
        col((1 << 14) % np.asarray(c.B.m, np.int64)),
    )


@partial(jax.jit, static_argnames=("ia", "ib", "interpret"))
def _madd_call(xa, xb, ya, yb, za, zb, pxp, pyp, has, inf,
               mA, mB, sigc, nB, wab, wba,
               amodb, bmoda, invab, invmib, cpA, cpB, oneA, oneB,
               c14a, c14b,
               ia: int, ib: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = xa.shape[1]
    grid = n // _TILE
    iap = pxp.shape[0]

    def col_spec(rows):
        return pl.BlockSpec((rows, _TILE), lambda i: (0, i),
                            memory_space=pltpu.VMEM)

    def const_spec(shape):
        return pl.BlockSpec(shape, lambda i: tuple(0 for _ in shape),
                            memory_space=pltpu.VMEM)

    consts = (mA, mB, sigc, nB, wab, wba, amodb, bmoda,
              invab, invmib, cpA, cpB, oneA, oneB, c14a, c14b)
    outs = (jax.ShapeDtypeStruct((ia, n), I32),
            jax.ShapeDtypeStruct((ib, n), I32)) * 3 + \
        (jax.ShapeDtypeStruct((1, n), I32),)
    return pl.pallas_call(
        _madd_kernel,
        out_shape=outs,
        grid=(grid,),
        in_specs=[col_spec(ia), col_spec(ib)] * 3
        + [col_spec(iap)] * 2
        + [col_spec(1), col_spec(1)]
        + [const_spec(a.shape) for a in consts],
        out_specs=tuple([col_spec(ia), col_spec(ib)] * 3
                        + [col_spec(1)]),
        interpret=interpret,
    )(xa, xb, ya, yb, za, zb, pxp, pyp, has, inf, *consts)


# ---------------------------------------------------------------------------
# Fused multi-window ladder: ALL windows of the table walk in ONE
# pallas_call. Windows ride the minor grid axis; the X/Y/Z state planes
# live in revisited VMEM output blocks for the whole ladder (the
# initial state is all-zeros-at-infinity, so window 0 zero-initializes
# them in-kernel), and only the pre-gathered per-window table rows
# stream from HBM. Per-window entry-infinity masks precompute as an
# exclusive any-scan of has = (digit > 0) — identical to the
# sequential inf &= ~has updates of the per-window path.
# ---------------------------------------------------------------------------


def ladder_enabled() -> bool:
    """Whole-ladder fusion: opt-in via CAP_TPU_PALLAS_LADDER=1.

    Deliberately default-OFF: bit-exact (parity suites cover it
    interpret-mode and compiled) but measured SLOWER on v5e — 47.6 ms
    vs 39.5 ms per-window @32k resident ES256 — because the kernel is
    VPU-bound and the mandatory pre-gather serializes ahead of it
    (docs/PERF.md round-4 A/B). Kept as a tested reference for parts
    with a different VPU/HBM balance.
    """
    v = os.environ.get("CAP_TPU_PALLAS_LADDER")
    return v is not None and v not in ("0", "false", "no")


def _ladder_kernel(g_ref, has_ref, inf_ref,
                   mA_ref, mB_ref, sigc_ref, nB_ref,
                   wab_ref, wba_ref,
                   amodb_ref, bmoda_ref, invab_ref, invmib_ref,
                   cpA_ref, cpB_ref, oneA_ref, oneB_ref,
                   c14a_ref, c14b_ref,
                   oxa_ref, oxb_ref, oya_ref, oyb_ref, oza_ref, ozb_ref,
                   deg_ref, *, ia: int, ib: int):
    from jax.experimental import pallas as pl

    w = pl.program_id(1)

    @pl.when(w == 0)
    def _init():
        # Ladder starts at infinity: zero planes + inf=1 (the window-0
        # inf mask is all-ones by construction of the entry-inf scan).
        for ref in (oxa_ref, oxb_ref, oya_ref, oyb_ref, oza_ref,
                    ozb_ref, deg_ref):
            ref[:] = jnp.zeros(ref.shape, ref.dtype)

    from .ec_rns import unpack_pt

    iap = max(ia, ib)
    g = g_ref[:][0]                     # [1, 2*iap, T] → [2*iap, T]
    x2 = unpack_pt(g[:iap], ia, ib)
    y2 = unpack_pt(g[iap:], ia, ib)
    oxa, oxb, oya, oyb, oza, ozb, deg = _madd_math(
        (oxa_ref[:], oxb_ref[:]), (oya_ref[:], oyb_ref[:]),
        (oza_ref[:], ozb_ref[:]), x2, y2,
        has_ref[:][0], inf_ref[:][0],
        mA_ref[:], mB_ref[:], sigc_ref[:], nB_ref[:],
        wab_ref[:], wba_ref[:],
        amodb_ref[:], bmoda_ref[:], invab_ref[:], invmib_ref[:],
        cpA_ref[:], cpB_ref[:], oneA_ref[:], oneB_ref[:],
        c14a_ref[:], c14b_ref[:])
    oxa_ref[:] = oxa
    oxb_ref[:] = oxb
    oya_ref[:] = oya
    oyb_ref[:] = oyb
    oza_ref[:] = oza
    ozb_ref[:] = ozb
    deg_ref[:] = deg_ref[:] | deg


@partial(jax.jit,
         static_argnames=("ia", "ib", "n_windows", "interpret"))
def _ladder_call(G, has, inf,
                 mA, mB, sigc, nB, wab, wba,
                 amodb, bmoda, invab, invmib, cpA, cpB, oneA, oneB,
                 c14a, c14b,
                 ia: int, ib: int, n_windows: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    iap = max(ia, ib)
    m = has.shape[2]
    grid = (m // _TILE, n_windows)

    def state_spec(rows):
        # Same block for every window step at fixed tile: the state
        # stays VMEM-resident across the whole ladder and flushes to
        # HBM once per tile.
        return pl.BlockSpec((rows, _TILE), lambda t, w: (0, t),
                            memory_space=pltpu.VMEM)

    # 3-D table blocks: the channel axis spans the FULL dimension (the
    # Mosaic block rule needs last-two block dims divisible by (8, 128)
    # or equal to the array's), window rides the leading axis.
    g_spec = pl.BlockSpec((1, 2 * iap, _TILE), lambda t, w: (w, 0, t),
                          memory_space=pltpu.VMEM)
    win_spec = pl.BlockSpec((1, 1, _TILE), lambda t, w: (w, 0, t),
                            memory_space=pltpu.VMEM)

    def const_spec(shape):
        return pl.BlockSpec(shape, lambda t, w: tuple(0 for _ in shape),
                            memory_space=pltpu.VMEM)

    consts = (mA, mB, sigc, nB, wab, wba, amodb, bmoda,
              invab, invmib, cpA, cpB, oneA, oneB, c14a, c14b)
    outs = (jax.ShapeDtypeStruct((ia, m), I32),
            jax.ShapeDtypeStruct((ib, m), I32)) * 3 + \
        (jax.ShapeDtypeStruct((1, m), I32),)
    return pl.pallas_call(
        partial(_ladder_kernel, ia=ia, ib=ib),
        out_shape=outs,
        grid=grid,
        in_specs=[g_spec, win_spec, win_spec]
        + [const_spec(a.shape) for a in consts],
        out_specs=tuple([state_spec(ia), state_spec(ib)] * 3
                        + [state_spec(1)]),
        interpret=interpret,
    )(G, has, inf, *consts)


def ladder_fused(c, tab, d_all, row0_all, interpret: bool = False):
    """Run the whole window ladder in one kernel.

    tab: fused [rows, 2I] x‖y window table (ec_rns layout);
    d_all / row0_all: [W, M] per-window digits and table-row bases
    (M = lane count, both accumulator chains concatenated).
    Returns (X, Y, Z, inf, deg) exactly as the per-window fori_loop:
    residue-plane pairs, final infinity mask, accumulated degeneracy.
    """
    ia, ib = c.A.count, c.B.count
    iap = max(ia, ib)
    n_windows, m = d_all.shape
    has_all = d_all > 0
    idx = row0_all + jnp.where(has_all, d_all - 1, 0)
    g = jnp.take(tab, idx.reshape(-1), axis=0)       # [W*M, 2*iap]
    G = g.reshape(n_windows, m, 2 * iap).transpose(0, 2, 1)
    has_i = has_all.astype(I32)
    hc = jnp.cumsum(has_i, axis=0)
    inf_i = ((hc - has_i) == 0).astype(I32)          # ENTRY infinity
    pad = (-m) % _TILE
    if pad:
        G = jnp.pad(G, ((0, 0), (0, 0), (0, pad)))
        has_i = jnp.pad(has_i, ((0, 0), (0, pad)))
        # padding lanes: inf=1, has=0 → zero planes pass through
        inf_i = jnp.pad(inf_i, ((0, 0), (0, pad)), constant_values=1)
    # [W, 1, M]: singleton middle axis keeps Mosaic's last-two-dims
    # block rule satisfied (block (1, 1, TILE))
    has_i = has_i[:, None, :]
    inf_i = inf_i[:, None, :]
    out = _ladder_call(G, has_i, inf_i, *_ctx_consts(c),
                       ia=ia, ib=ib, n_windows=n_windows,
                       interpret=interpret)
    oxa, oxb, oya, oyb, oza, ozb, deg = out
    sl = slice(0, m)
    inf_fin = hc[n_windows - 1] == 0
    return ((oxa[:, sl], oxb[:, sl]), (oya[:, sl], oyb[:, sl]),
            (oza[:, sl], ozb[:, sl]), inf_fin, deg[0, sl] != 0)


def madd_fused(c, X, Y, Z, inf, has, x2p, y2p, interpret: bool = False):
    """Fused add_from_table step: returns (X', Y', Z', deg_bool).

    X/Y/Z: (A, B) residue-plane pairs [I, N]; x2p/y2p: PACKED table
    words [max(I_A, I_B), N] (A|B<<16, ec_rns._pack_residue_rows —
    unpacked in-kernel); inf/has: [N] bool. The caller keeps the cheap
    [N]-wide bookkeeping (inf' = inf & ~has, deg accumulation) in XLA.
    """
    ia = X[0].shape[0]
    ib = X[1].shape[0]
    n = X[0].shape[1]
    pad = (-n) % _TILE

    def p1(a):
        return jnp.pad(a, ((0, 0), (0, pad))) if pad else a

    def p2(pair):
        return (p1(pair[0]), p1(pair[1]))

    Xp, Yp, Zp = p2(X), p2(Y), p2(Z)
    has_i = jnp.pad(has.astype(I32)[None, :], ((0, 0), (0, pad)))
    # padding lanes: inf=1, has=0 → pass-through of zero planes
    inf_i = jnp.pad(inf.astype(I32)[None, :], ((0, 0), (0, pad)),
                    constant_values=1)
    out = _madd_call(Xp[0], Xp[1], Yp[0], Yp[1], Zp[0], Zp[1],
                     p1(x2p), p1(y2p), has_i, inf_i,
                     *_ctx_consts(c), ia=ia, ib=ib,
                     interpret=interpret)
    oxa, oxb, oya, oyb, oza, ozb, deg = out
    sl = slice(0, n)
    return ((oxa[:, sl], oxb[:, sl]), (oya[:, sl], oyb[:, sl]),
            (oza[:, sl], ozb[:, sl]), deg[0, sl] != 0)
