"""Fused Edwards mixed-add (extended + precomputed) as one Pallas TPU
kernel — the Ed25519 ladder analog of pallas_madd.

Each of the 32 ladder windows runs ``ed25519_rns._edw_madd_rns``: 7
field multiplies (each a full Bajard/Kawamura REDC) plus lazy
adds/subs on (X, Y, Z, T) residue-plane pairs. Under XLA those REDCs
materialize their [I, 2N] neighborhoods to HBM between kernels even
with the fused-REDC kernel serving each multiply (pallas_redc); this
kernel runs the WHOLE mixed-add on VMEM tiles, touching HBM once for
inputs and once for outputs. The Edwards addition law here is complete
(a = -1, add-2008-hwcd-3) and the window tables carry identity rows
for digit 0, so — unlike the Jacobian kernel — there are no masks, no
degeneracy probe, and no infinity lift.

Numerical contract: bit-identical to _edw_madd_rns (same fixed-point
ops via pallas_redc.make_rns_ops — ``rmul_many``'s lane concatenation
is elementwise per lane, so per-pair rmuls produce the same digits).
Parity pinned by tests/test_pallas_madd.py in interpret mode and
compiled on chip. Default ON for TPU once measured faster (A/B in
docs/PERF.md); CAP_TPU_PALLAS_EDW=1/0 overrides.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict

import numpy as np

import jax
import jax.numpy as jnp

from .pallas_redc import make_rns_ops

I32 = jnp.int32

_TILE = int(os.environ.get("CAP_TPU_EDW_TILE", 512))  # lanes/step


def enabled() -> bool:
    """Fused Edwards mixed-add: CAP_TPU_PALLAS_EDW=1/0 overrides.

    Default ON for the TPU backend (GPU keeps the XLA path, like
    pallas_madd): three same-minutes on-chip A/B pairs @16k resident
    EdDSA, min-of-3 slope, fused vs per-REDC-fused baseline —
    619→652, 623→707, 658→985 k verifies/s; fused won every pair
    (the spread is dispatch/tunnel noise). CPU defaults to the XLA
    path (the parity reference); CAP_TPU_PALLAS_EDW=1 on CPU runs
    interpret mode, which the parity tests use.
    """
    v = os.environ.get("CAP_TPU_PALLAS_EDW")
    if v is not None:
        return v not in ("0", "false", "no")
    return jax.default_backend() == "tpu"


def _edw_kernel(xa_ref, xb_ref, ya_ref, yb_ref, za_ref, zb_ref,
                ta_ref, tb_ref,
                yma_ref, ymb_ref, ypa_ref, ypb_ref, t2a_ref, t2b_ref,
                mA_ref, mB_ref, sigc_ref, nB_ref,
                wab_ref, wba_ref,
                amodb_ref, bmoda_ref, invab_ref, invmib_ref,
                cpA_ref, cpB_ref, c14a_ref, c14b_ref,
                oxa_ref, oxb_ref, oya_ref, oyb_ref, oza_ref, ozb_ref,
                ota_ref, otb_ref):
    _, _, rmul, radd, rsub, _ = make_rns_ops(
        mA_ref[:], mB_ref[:], sigc_ref[:], nB_ref[:],
        wab_ref[:], wba_ref[:],
        amodb_ref[:], bmoda_ref[:], invab_ref[:], invmib_ref[:],
        cpA_ref[:], cpB_ref[:], c14a_ref[:], c14b_ref[:])

    X = (xa_ref[:], xb_ref[:])
    Y = (ya_ref[:], yb_ref[:])
    Z = (za_ref[:], zb_ref[:])
    T = (ta_ref[:], tb_ref[:])
    ym = (yma_ref[:], ymb_ref[:])
    yp = (ypa_ref[:], ypb_ref[:])
    t2 = (t2a_ref[:], t2b_ref[:])

    # _edw_madd_rns, layer for layer (digit/value bounds live there).
    a = rmul(rsub(Y, X, 4, 1), ym)
    b = rmul(radd(Y, X), yp)
    cc = rmul(T, t2)
    d = radd(Z, Z)
    e = rsub(b, a, 4, 1)
    f = rsub(d, cc, 4, 1)
    g = radd(d, cc)
    h = radd(b, a)
    X3 = rmul(e, f)
    Y3 = rmul(g, h)
    Z3 = rmul(f, g)
    T3 = rmul(e, h)

    oxa_ref[:], oxb_ref[:] = X3
    oya_ref[:], oyb_ref[:] = Y3
    oza_ref[:], ozb_ref[:] = Z3
    ota_ref[:], otb_ref[:] = T3


_CONSTS: Dict[int, tuple] = {}


def _ctx_consts(c) -> tuple:
    """Kernel constant set for a FieldRNSContext (host numpy, cached).

    Reuses pallas_redc's cached 14-entry REDC constant set (one
    derivation to keep in sync), inserting only the pre-transposed
    c·p residue tables this kernel's rsub needs.
    """
    from . import pallas_redc

    def build():
        # pallas_redc's 12-entry tuple ends (..., invmib, c14a, c14b);
        # this kernel's signature wants cpA/cpB before the c14 pair.
        r = pallas_redc._ctx_consts(c)
        return r[:10] + (
            np.ascontiguousarray(np.asarray(c.cp_A, np.int32).T),
            np.ascontiguousarray(np.asarray(c.cp_B, np.int32).T),
        ) + r[10:]

    return pallas_redc.pinned_ctx_cache(_CONSTS, c, build)


@partial(jax.jit, static_argnames=("ia", "ib", "interpret"))
def _edw_call(xa, xb, ya, yb, za, zb, ta, tb,
              yma, ymb, ypa, ypb, t2a, t2b,
              mA, mB, sigc, nB, wab, wba,
              amodb, bmoda, invab, invmib, cpA, cpB, c14a, c14b,
              ia: int, ib: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = xa.shape[1]
    grid = (n // _TILE,)

    def col_spec(rows):
        return pl.BlockSpec((rows, _TILE), lambda i: (0, i),
                            memory_space=pltpu.VMEM)

    def const_spec(shape):
        return pl.BlockSpec(shape, lambda i: tuple(0 for _ in shape),
                            memory_space=pltpu.VMEM)

    consts = (mA, mB, sigc, nB, wab, wba, amodb, bmoda,
              invab, invmib, cpA, cpB, c14a, c14b)
    outs = (jax.ShapeDtypeStruct((ia, n), I32),
            jax.ShapeDtypeStruct((ib, n), I32)) * 4
    return pl.pallas_call(
        _edw_kernel,
        out_shape=outs,
        grid=grid,
        in_specs=[col_spec(ia), col_spec(ib)] * 7
        + [const_spec(a.shape) for a in consts],
        out_specs=tuple([col_spec(ia), col_spec(ib)] * 4),
        interpret=interpret,
    )(xa, xb, ya, yb, za, zb, ta, tb, yma, ymb, ypa, ypb, t2a, t2b,
      *consts)


def edw_madd_fused(c, X, Y, Z, T, ym, yp, t2, interpret: bool = False):
    """Fused _edw_madd_rns step: returns (X', Y', Z', T').

    All operands are (A, B) residue-plane pairs [I, N]; N pads to the
    tile size with zero lanes (every fix maps zeros to valid residues
    and the caller's slices drop them).
    """
    ia = X[0].shape[0]
    ib = X[1].shape[0]
    n = X[0].shape[1]
    pad = (-n) % _TILE

    def p2(pair):
        if not pad:
            return pair
        return (jnp.pad(pair[0], ((0, 0), (0, pad))),
                jnp.pad(pair[1], ((0, 0), (0, pad))))

    Xp, Yp, Zp, Tp = p2(X), p2(Y), p2(Z), p2(T)
    ymp, ypp, t2p = p2(ym), p2(yp), p2(t2)
    out = _edw_call(Xp[0], Xp[1], Yp[0], Yp[1], Zp[0], Zp[1],
                    Tp[0], Tp[1], ymp[0], ymp[1], ypp[0], ypp[1],
                    t2p[0], t2p[1], *_ctx_consts(c),
                    ia=ia, ib=ib, interpret=interpret)
    sl = slice(0, n)
    return ((out[0][:, sl], out[1][:, sl]),
            (out[2][:, sl], out[3][:, sl]),
            (out[4][:, sl], out[5][:, sl]),
            (out[6][:, sl], out[7][:, sl]))
