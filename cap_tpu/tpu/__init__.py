"""TPU verify engine.

The performance layer of cap_tpu: batched big-number and elliptic-curve
arithmetic as JAX programs XLA-compiled for TPU, plus the
batching/bucketing runtime that feeds it. A hand-written fused Pallas
REDC kernel exists (pallas_redc.py, CAP_TPU_PALLAS=1) but the measured
A/B (docs/PERF.md) has XLA's fusion ahead, so the XLA path is the
default. The reference has no native/accelerated components
(SURVEY.md §2) — this subsystem is the new framework's replacement for
the Go stdlib crypto inner loops (crypto/rsa, crypto/ecdsa,
crypto/ed25519).

Layout convention: big integers are little-endian base-2^16 limb vectors
stored **limb-first**: an array of shape [K, N] holds N numbers of K
limbs, so the batch axis N rides the TPU's 128-wide vector lanes and
limb shifts are cheap sublane moves.
"""
