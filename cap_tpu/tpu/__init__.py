"""TPU verify engine.

The performance layer of cap_tpu: batched big-number and elliptic-curve
arithmetic as JAX programs XLA-compiled for TPU, plus the
batching/bucketing runtime that feeds it. Hand-written Pallas kernels
cover the EC/Ed and post-quantum hot loops and default ON for TPU
backends — the fused mixed-add (pallas_madd.py, CAP_TPU_PALLAS_MADD),
the fused REDC (pallas_redc.py, CAP_TPU_PALLAS), the fused 8-stage
NTT (pallas_ntt.py, CAP_TPU_PALLAS_NTT), and the Keccak-f[1600] lane
kernel (pallas_keccak.py, CAP_TPU_PALLAS_KECCAK); A/Bs in
docs/PERF.md, CPU keeps the XLA path as the parity reference. The reference has no
native/accelerated components
(SURVEY.md §2) — this subsystem is the new framework's replacement for
the Go stdlib crypto inner loops (crypto/rsa, crypto/ecdsa,
crypto/ed25519).

Layout convention: big integers are little-endian base-2^16 limb vectors
stored **limb-first**: an array of shape [K, N] holds N numbers of K
limbs, so the batch axis N rides the TPU's 128-wide vector lanes and
limb shifts are cheap sublane moves.
"""
