package captpu

// Full CVB1 frame codec: the checksummed (7/8), traced (9/10), keys
// (11/12), peer-fill (13/14), stats (5/6) and shm (15/16) frame pairs
// on top of the plain pair captpu.go has always spoken. Byte layouts
// mirror cap_tpu/serve/protocol.py exactly; the committed golden
// vectors in testdata/ pin every encoder and decoder here against the
// Python implementation (the worker's source of truth).

import (
	"bufio"
	"bytes"
	"encoding/binary"
	"errors"
	"fmt"
	"hash/crc32"
	"io"
)

const (
	typeStatsReq      = 5
	typeStatsRsp      = 6
	typeVerifyReqCRC  = 7
	typeVerifyRspCRC  = 8
	typeVerifyReqTr   = 9
	typeVerifyRspTr   = 10
	typeKeysPush      = 11
	typeKeysAck       = 12
	typePeerFill      = 13
	typePeerAck       = 14
	typeShmAttach     = 15
	typeShmAck        = 16
	maxFrameEntries   = 1 << 20
	maxTraceBytes     = 64
)

// ErrCorrupt is returned when a checksummed frame's CRC32 trailer
// does not match its bytes (the Python side raises FrameCorruptError).
var ErrCorrupt = errors.New("captpu: frame crc mismatch")

func appendU32(b []byte, v uint32) []byte {
	var u [4]byte
	binary.LittleEndian.PutUint32(u[:], v)
	return append(b, u[:]...)
}

func appendCRC(b []byte) []byte {
	return appendU32(b, crc32.ChecksumIEEE(b))
}

func validTrace(trace string) bool {
	if len(trace) == 0 || len(trace) > maxTraceBytes {
		return false
	}
	for i := 0; i < len(trace); i++ {
		c := trace[i]
		if !(c >= '0' && c <= '9' || c >= 'a' && c <= 'f') {
			return false
		}
	}
	return true
}

// encodeRequestEx builds one verify-request frame: plain (type 1),
// checksummed (type 7, crc=true) or traced (type 9, trace != "" —
// traced frames are always checksummed, matching protocol.py).
func encodeRequestEx(tokens []string, crc bool, trace string) ([]byte, error) {
	ftype := byte(typeVerifyReq)
	if trace != "" {
		if !validTrace(trace) {
			return nil, fmt.Errorf("captpu: invalid trace id %q", trace)
		}
		ftype = typeVerifyReqTr
	} else if crc {
		ftype = typeVerifyReqCRC
	}
	size := 9 + len(trace) + 1
	for _, t := range tokens {
		if len(t) > maxEntryBytes {
			return nil, fmt.Errorf("captpu: token exceeds %d bytes", maxEntryBytes)
		}
		size += 4 + len(t)
	}
	if size > maxFrameBytes {
		return nil, fmt.Errorf("captpu: frame exceeds %d bytes", maxFrameBytes)
	}
	frame := make([]byte, 0, size+4)
	frame = appendU32(frame, magic)
	frame = append(frame, ftype)
	frame = appendU32(frame, uint32(len(tokens)))
	if trace != "" {
		frame = append(frame, byte(len(trace)))
		frame = append(frame, trace...)
	}
	for _, t := range tokens {
		frame = appendU32(frame, uint32(len(t)))
		frame = append(frame, t...)
	}
	if ftype != typeVerifyReq {
		frame = appendCRC(frame)
	}
	return frame, nil
}

// encodeControl builds a checksummed one-entry request-shaped frame
// (keys push / peer fill / shm attach): the r10 control-frame shape.
func encodeControl(ftype byte, payload []byte) ([]byte, error) {
	if len(payload) > maxEntryBytes {
		return nil, fmt.Errorf("captpu: control payload exceeds %d bytes", maxEntryBytes)
	}
	frame := make([]byte, 0, 9+4+len(payload)+4)
	frame = appendU32(frame, magic)
	frame = append(frame, ftype)
	frame = appendU32(frame, 1)
	frame = appendU32(frame, uint32(len(payload)))
	frame = append(frame, payload...)
	return appendCRC(frame), nil
}

func encodePing() []byte {
	f := make([]byte, 0, 9)
	f = appendU32(f, magic)
	f = append(f, typePing)
	return appendU32(f, 0)
}

func encodeStatsReq() []byte {
	f := make([]byte, 0, 9)
	f = appendU32(f, magic)
	f = append(f, typeStatsReq)
	return appendU32(f, 0)
}

// respEntry is one response-shaped entry: status 0 = verified (payload
// is claims JSON), 1 = rejected (payload is the error class+message).
type respEntry struct {
	status  byte
	payload []byte
}

// respFrame is one parsed response-direction frame.
type respFrame struct {
	ftype   byte
	trace   string
	entries []respEntry
}

// readFrame reads and validates one response-direction frame (verify
// response in all three flavors, pong, stats, keys/peer/shm acks).
// Checksummed types verify the CRC trailer before anything else is
// trusted, exactly like the Python parser.
func readFrame(r *bufio.Reader) (*respFrame, error) {
	hdr := make([]byte, 9)
	if _, err := io.ReadFull(r, hdr); err != nil {
		return nil, fmt.Errorf("captpu: recv header: %w", err)
	}
	if binary.LittleEndian.Uint32(hdr[0:4]) != magic {
		return nil, errors.New("captpu: bad magic in response")
	}
	ftype := hdr[4]
	count := binary.LittleEndian.Uint32(hdr[5:9])
	if count > maxFrameEntries {
		return nil, errors.New("captpu: response entry count exceeds bound")
	}
	checksummed := ftype == typeVerifyRspCRC || ftype == typeVerifyRspTr ||
		ftype == typeKeysAck || ftype == typePeerAck || ftype == typeShmAck
	body := hdr[:] // every byte the CRC covers
	out := &respFrame{ftype: ftype}
	switch ftype {
	case typePong:
		if count != 0 {
			return nil, errors.New("captpu: pong with nonzero count")
		}
		return out, nil
	case typeVerifyRsp, typeVerifyRspCRC, typeVerifyRspTr,
		typeStatsRsp, typeKeysAck, typePeerAck, typeShmAck:
	default:
		return nil, fmt.Errorf("captpu: unexpected frame type %d", ftype)
	}
	if ftype == typeVerifyRspTr {
		tl := make([]byte, 1)
		if _, err := io.ReadFull(r, tl); err != nil {
			return nil, err
		}
		if tl[0] == 0 || int(tl[0]) > maxTraceBytes {
			return nil, errors.New("captpu: bad trace-context length")
		}
		tb := make([]byte, tl[0])
		if _, err := io.ReadFull(r, tb); err != nil {
			return nil, err
		}
		body = append(body, tl[0])
		body = append(body, tb...)
		out.trace = string(tb)
	}
	total := 0
	entry := make([]byte, 5)
	out.entries = make([]respEntry, 0, count)
	for i := uint32(0); i < count; i++ {
		if _, err := io.ReadFull(r, entry); err != nil {
			return nil, fmt.Errorf("captpu: recv entry: %w", err)
		}
		status := entry[0]
		ln := binary.LittleEndian.Uint32(entry[1:5])
		if !checksummed && status > 1 {
			return nil, fmt.Errorf("captpu: bad status byte %d", status)
		}
		total += int(ln)
		if ln > maxEntryBytes || total > maxFrameBytes {
			return nil, errors.New("captpu: oversized response entry")
		}
		payload := make([]byte, ln)
		if _, err := io.ReadFull(r, payload); err != nil {
			return nil, fmt.Errorf("captpu: recv payload: %w", err)
		}
		body = append(body, entry...)
		body = append(body, payload...)
		out.entries = append(out.entries, respEntry{status, payload})
	}
	if checksummed {
		trailer := make([]byte, 4)
		if _, err := io.ReadFull(r, trailer); err != nil {
			return nil, fmt.Errorf("captpu: recv crc: %w", err)
		}
		if binary.LittleEndian.Uint32(trailer) != crc32.ChecksumIEEE(body) {
			return nil, ErrCorrupt
		}
		// deferred status validation, matching the Python parser
		for _, e := range out.entries {
			if e.status > 1 {
				return nil, fmt.Errorf("captpu: bad status byte %d", e.status)
			}
		}
		if out.trace != "" && !validTrace(out.trace) {
			return nil, errors.New("captpu: trace-context not lowercase hex")
		}
	}
	return out, nil
}

// parseFrameBytes parses one complete frame held in a byte slice (the
// shm ring hands whole records across) via the same reader.
func parseFrameBytes(b []byte) (*respFrame, error) {
	return readFrame(bufio.NewReader(bytes.NewReader(b)))
}
