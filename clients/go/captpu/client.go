package captpu

// Production-grade pooled client: cap's KeySet seam over a fleet of
// cap_tpu verify workers, mirroring the Python FleetClient's
// availability contract — per-attempt deadlines, endpoint rotation,
// hedged retry on a healthy peer, and a terminal pure-Go fallback
// (never wrong, at worst slow). Underneath, each connection
// negotiates the zero-copy shared-memory transport (CVB1 type 15)
// when Options.Transport allows and silently keeps the socket when
// the worker refuses or predates it.

import (
	"bufio"
	"context"
	"encoding/json"
	"errors"
	"fmt"
	"math/rand"
	"net"
	"os"
	"strings"
	"sync"
	"sync/atomic"
	"time"
)

// Options configures Client. The zero value of every field gets a
// production default in NewClient.
type Options struct {
	// Addrs lists worker endpoints: "host:port" (TCP) or
	// "unix:///path/to.sock". At least one is required.
	Addrs []string

	// PoolSize is the number of pooled connections per endpoint
	// (default 2). Calls beyond the pool dial extra connections that
	// are discarded when the pool is full — the worker's batcher
	// coalesces concurrent callers regardless.
	PoolSize int

	// CRC selects the checksummed frame pair (types 7/8): byte
	// corruption anywhere on the path surfaces as ErrCorrupt instead
	// of a wrong verdict. The fleet router always sets this.
	CRC bool

	// Transport: "auto" (default — negotiate shm, fall back to the
	// socket), "socket" (never negotiate), or "shm" (negotiate and
	// FAIL dial when refused; for tests and benchmarks that must not
	// silently measure the wrong transport).
	Transport string

	// ShmDir is where per-connection region files live (default
	// CAP_SHM_DIR, then /dev/shm when present, then os.TempDir()).
	ShmDir string

	// RingBytes sizes each ring (request and response; default 1 MiB,
	// rounded up to a power of two). The largest single frame a ring
	// carries is RingBytes/2.
	RingBytes int

	// AttemptTimeout bounds ONE wire exchange on one worker (default
	// 5s). DialTimeout bounds connection establishment (default 10s).
	AttemptTimeout time.Duration
	DialTimeout    time.Duration

	// HedgeAfter launches a duplicate attempt on the next endpoint
	// when the primary has not answered yet (default 250ms; negative
	// disables; needs >= 2 endpoints). First success wins — the
	// FleetClient hedge contract.
	HedgeAfter time.Duration

	// Retries is the number of extra full endpoint rounds after the
	// first (default 2), with Backoff sleep between rounds (default
	// 50ms, doubled per round, ±50% jitter).
	Retries int
	Backoff time.Duration

	// Fallback, when set, is the terminal availability tier: if every
	// endpoint round fails, tokens are verified through it one by one
	// (e.g. the pure-Go reference library wrapped as a KeySet).
	Fallback KeySet
}

type endpoint struct{ network, addr string }

func parseAddr(a string) endpoint {
	if strings.HasPrefix(a, "unix://") {
		return endpoint{"unix", strings.TrimPrefix(a, "unix://")}
	}
	return endpoint{"tcp", a}
}

// wireConn is one connection: the socket plus, when negotiated, its
// shm region. Owned by one goroutine at a time (the pool enforces it).
type wireConn struct {
	nc        net.Conn
	br        *bufio.Reader
	shm       *shmRegion
	transport string
}

func (w *wireConn) close() {
	w.nc.Close()
	if w.shm != nil {
		w.shm.close(true)
	}
}

// exchange sends one encoded frame and reads one response frame over
// whichever transport this connection negotiated.
func (w *wireConn) exchange(frame []byte, deadline time.Time) (*respFrame, error) {
	if w.shm == nil {
		w.nc.SetDeadline(deadline)
		defer w.nc.SetDeadline(time.Time{})
		if _, err := w.nc.Write(frame); err != nil {
			return nil, fmt.Errorf("captpu: send: %w", err)
		}
		return readFrame(w.br)
	}
	if err := w.shm.writeRecord(ringReq, frame, deadline); err != nil {
		return nil, err
	}
	rec, err := w.shm.readRecord(ringResp, deadline, w.workerAlive)
	if err != nil {
		return nil, err
	}
	return parseFrameBytes(rec)
}

// workerAlive probes the liveness socket without consuming data: a
// dead worker means the shm response will never come.
func (w *wireConn) workerAlive() error {
	w.nc.SetReadDeadline(time.Now().Add(time.Millisecond))
	defer w.nc.SetReadDeadline(time.Time{})
	one := make([]byte, 1)
	n, err := w.nc.Read(one)
	if n > 0 {
		return errors.New("captpu: unexpected bytes on shm liveness socket")
	}
	if err == nil {
		return nil
	}
	var ne net.Error
	if errors.As(err, &ne) && ne.Timeout() {
		return nil // no data — worker alive
	}
	return fmt.Errorf("captpu: worker gone: %w", err)
}

type connPool struct {
	ep   endpoint
	o    *Options
	mu   sync.Mutex
	idle []*wireConn
}

func (p *connPool) get() (*wireConn, error) {
	p.mu.Lock()
	if n := len(p.idle); n > 0 {
		w := p.idle[n-1]
		p.idle = p.idle[:n-1]
		p.mu.Unlock()
		return w, nil
	}
	p.mu.Unlock()
	return dialWire(p.ep, p.o)
}

func (p *connPool) put(w *wireConn) {
	p.mu.Lock()
	if len(p.idle) < p.o.PoolSize {
		p.idle = append(p.idle, w)
		p.mu.Unlock()
		return
	}
	p.mu.Unlock()
	w.close()
}

func (p *connPool) closeAll() {
	p.mu.Lock()
	idle := p.idle
	p.idle = nil
	p.mu.Unlock()
	for _, w := range idle {
		w.close()
	}
}

func shmDir(o *Options) string {
	if o.ShmDir != "" {
		return o.ShmDir
	}
	if d := os.Getenv("CAP_SHM_DIR"); d != "" {
		return d
	}
	if st, err := os.Stat("/dev/shm"); err == nil && st.IsDir() {
		return "/dev/shm"
	}
	return os.TempDir()
}

func dialSocket(ep endpoint, o *Options) (net.Conn, error) {
	d := net.Dialer{Timeout: o.DialTimeout}
	nc, err := d.Dial(ep.network, ep.addr)
	if err != nil {
		return nil, fmt.Errorf("captpu: dial %s %s: %w", ep.network, ep.addr, err)
	}
	if tc, ok := nc.(*net.TCPConn); ok {
		tc.SetNoDelay(true)
	}
	return nc, nil
}

// dialWire connects and, when the transport allows, negotiates the
// shm attach. The fallback contract: a status-1 ack keeps the SAME
// socket; a dropped connection (stale worker that never learned frame
// type 15) redials socket-only. Transport "shm" turns both into dial
// errors instead — callers asked for exactly that transport.
func dialWire(ep endpoint, o *Options) (*wireConn, error) {
	nc, err := dialSocket(ep, o)
	if err != nil {
		return nil, err
	}
	w := &wireConn{nc: nc, br: bufio.NewReaderSize(nc, 1<<16), transport: "socket"}
	mode := o.Transport
	if mode == "" {
		mode = "auto"
	}
	if mode == "socket" {
		return w, nil
	}
	size := uint64(shmMinRing)
	want := uint64(o.RingBytes)
	if want == 0 {
		want = 1 << 20
	}
	for size < want && size < shmMaxRing {
		size <<= 1
	}
	path := fmt.Sprintf("%s/cap-shm-go-%d-%08x", shmDir(o), os.Getpid(), rand.Uint32())
	region, err := createShmRegion(path, size, size, rand.Uint32()|1)
	if err != nil {
		if mode == "shm" {
			nc.Close()
			return nil, err
		}
		return w, nil // no shared memory here: keep the socket
	}
	payload := []byte(`{"op":"attach","path":"` + path + `","version":1}`)
	frame, err := encodeControl(typeShmAttach, payload)
	if err != nil {
		region.close(true)
		if mode == "shm" {
			nc.Close()
			return nil, err
		}
		return w, nil
	}
	deadline := time.Now().Add(o.AttemptTimeout)
	w.nc.SetDeadline(deadline)
	_, werr := w.nc.Write(frame)
	var rf *respFrame
	if werr == nil {
		rf, err = readFrame(w.br)
	} else {
		err = werr
	}
	w.nc.SetDeadline(time.Time{})
	if err != nil {
		// stale worker dropped the unknown frame (or died): redial
		// socket-only — negotiation must never cost a working client
		region.close(true)
		nc.Close()
		if mode == "shm" {
			return nil, fmt.Errorf("captpu: shm attach failed: %w", err)
		}
		nc2, err2 := dialSocket(ep, o)
		if err2 != nil {
			return nil, err2
		}
		return &wireConn{nc: nc2, br: bufio.NewReaderSize(nc2, 1<<16), transport: "socket"}, nil
	}
	if rf.ftype != typeShmAck || len(rf.entries) != 1 || rf.entries[0].status != 0 {
		// negotiated refusal: the worker keeps serving this very
		// connection over the socket
		region.close(true)
		if mode == "shm" {
			nc.Close()
			msg := "refused"
			if rf != nil && len(rf.entries) == 1 {
				msg = string(rf.entries[0].payload)
			}
			return nil, fmt.Errorf("captpu: shm attach refused: %s", msg)
		}
		return w, nil
	}
	w.shm = region
	w.transport = "shm"
	return w, nil
}

// Client is a production BatchKeySet over one or more verify workers.
type Client struct {
	o      Options
	pools  []*connPool
	rr     uint64
	closed int32

	// admission pushback (docs/SERVE.md §Admission & fairness): a
	// ThrottledError's retry-after hint opens a window during which
	// the client does not hedge — duplicating a throttled batch
	// doubles exactly the load the worker is shedding.
	pushbackMu    sync.Mutex
	pushbackUntil time.Time
}

// notePushback extends the pushback window from a worker hint.
func (c *Client) notePushback(d time.Duration) {
	if d <= 0 {
		d = c.o.Backoff
	}
	if d > 30*time.Second {
		d = 30 * time.Second
	}
	c.pushbackMu.Lock()
	if t := time.Now().Add(d); t.After(c.pushbackUntil) {
		c.pushbackUntil = t
	}
	c.pushbackMu.Unlock()
}

// PushbackActive reports whether a worker retry-after window is open.
func (c *Client) PushbackActive() bool {
	c.pushbackMu.Lock()
	defer c.pushbackMu.Unlock()
	return time.Now().Before(c.pushbackUntil)
}

// NewClient validates options, applies defaults, and verifies that at
// least one endpoint is dialable (the rest may join later).
func NewClient(o Options) (*Client, error) {
	if len(o.Addrs) == 0 {
		return nil, errors.New("captpu: Options.Addrs is required")
	}
	if o.PoolSize <= 0 {
		o.PoolSize = 2
	}
	if o.AttemptTimeout <= 0 {
		o.AttemptTimeout = 5 * time.Second
	}
	if o.DialTimeout <= 0 {
		o.DialTimeout = 10 * time.Second
	}
	if o.HedgeAfter < 0 {
		o.HedgeAfter = 0
	} else if o.HedgeAfter == 0 {
		o.HedgeAfter = 250 * time.Millisecond
	}
	if o.Retries < 0 {
		o.Retries = 0
	} else if o.Retries == 0 {
		o.Retries = 2
	}
	if o.Backoff <= 0 {
		o.Backoff = 50 * time.Millisecond
	}
	switch o.Transport {
	case "", "auto", "socket", "shm":
	default:
		return nil, fmt.Errorf("captpu: unknown transport %q", o.Transport)
	}
	c := &Client{o: o}
	for _, a := range o.Addrs {
		c.pools = append(c.pools, &connPool{ep: parseAddr(a), o: &c.o})
	}
	w, err := c.pools[0].get()
	if err != nil {
		return nil, err
	}
	c.pools[0].put(w)
	return c, nil
}

// Transport reports the transport a pooled connection to the first
// endpoint negotiated ("shm" or "socket").
func (c *Client) Transport() (string, error) {
	w, err := c.pools[0].get()
	if err != nil {
		return "", err
	}
	tr := w.transport
	c.pools[0].put(w)
	return tr, nil
}

// Close drops every pooled connection. In-flight calls finish.
func (c *Client) Close() error {
	atomic.StoreInt32(&c.closed, 1)
	for _, p := range c.pools {
		p.closeAll()
	}
	return nil
}

// VerifySignature implements cap's KeySet seam for one token.
func (c *Client) VerifySignature(ctx context.Context, token string) (map[string]interface{}, error) {
	res, err := c.VerifyBatch(ctx, []string{token})
	if err != nil {
		return nil, err
	}
	if res[0].Err != nil {
		return nil, res[0].Err
	}
	return res[0].Claims, nil
}

// VerifyBatch verifies every token with per-attempt deadlines,
// endpoint rotation, hedged retry, and the terminal fallback.
func (c *Client) VerifyBatch(ctx context.Context, tokens []string) ([]Result, error) {
	if atomic.LoadInt32(&c.closed) != 0 {
		return nil, ErrClosed
	}
	if len(tokens) == 0 {
		return []Result{}, nil
	}
	frame, err := encodeRequestEx(tokens, c.o.CRC, "")
	if err != nil {
		return nil, err
	}
	start := int(atomic.AddUint64(&c.rr, 1))
	var lastErr error
	backoff := c.o.Backoff
	for round := 0; round <= c.o.Retries; round++ {
		for i := 0; i < len(c.pools); i++ {
			if ctx.Err() != nil {
				return nil, ctx.Err()
			}
			primary := c.pools[(start+i)%len(c.pools)]
			hedge := c.pools[(start+i+1)%len(c.pools)]
			if len(c.pools) == 1 {
				hedge = nil
			}
			res, err := c.attempt(ctx, primary, hedge, frame, len(tokens))
			if err == nil {
				return res, nil
			}
			lastErr = err
		}
		if round < c.o.Retries {
			jitter := time.Duration(rand.Int63n(int64(backoff))) - backoff/2
			select {
			case <-time.After(backoff + jitter):
			case <-ctx.Done():
				return nil, ctx.Err()
			}
			backoff *= 2
		}
	}
	if c.o.Fallback != nil {
		return c.fallbackVerify(ctx, tokens)
	}
	return nil, fmt.Errorf("captpu: all endpoints failed: %w", lastErr)
}

type attemptResult struct {
	res []Result
	err error
}

// attempt runs one exchange on the primary endpoint, hedging onto the
// peer when the primary is slow. First success wins; the losing
// attempt finishes in the background and returns its conn to its pool.
func (c *Client) attempt(ctx context.Context, primary, hedge *connPool, frame []byte, want int) ([]Result, error) {
	ch := make(chan attemptResult, 2)
	launched := 1
	go c.oneAttempt(primary, frame, want, ch)
	var hedgeTimer <-chan time.Time
	if hedge != nil && c.o.HedgeAfter > 0 && !c.PushbackActive() {
		hedgeTimer = time.After(c.o.HedgeAfter)
	}
	var lastErr error
	for {
		select {
		case r := <-ch:
			if r.err == nil {
				return r.res, nil
			}
			lastErr = r.err
			launched--
			if launched == 0 {
				return nil, lastErr
			}
		case <-hedgeTimer:
			hedgeTimer = nil
			launched++
			go c.oneAttempt(hedge, frame, want, ch)
		case <-ctx.Done():
			return nil, ctx.Err()
		}
	}
}

func (c *Client) oneAttempt(p *connPool, frame []byte, want int, ch chan<- attemptResult) {
	w, err := p.get()
	if err != nil {
		ch <- attemptResult{nil, err}
		return
	}
	rf, err := w.exchange(frame, time.Now().Add(c.o.AttemptTimeout))
	if err != nil {
		w.close() // unread bytes may be on the wire: poison
		ch <- attemptResult{nil, err}
		return
	}
	res, err := c.decodeVerify(rf, want)
	if err != nil {
		w.close()
		ch <- attemptResult{nil, err}
		return
	}
	p.put(w)
	ch <- attemptResult{res, nil}
}

func (c *Client) decodeVerify(rf *respFrame, want int) ([]Result, error) {
	wantType := byte(typeVerifyRsp)
	if c.o.CRC {
		// integrity must not be silently downgradable
		wantType = typeVerifyRspCRC
	}
	if rf.ftype != wantType {
		return nil, fmt.Errorf("captpu: expected response type %d, got %d", wantType, rf.ftype)
	}
	if len(rf.entries) != want {
		return nil, fmt.Errorf("captpu: response count %d != request %d", len(rf.entries), want)
	}
	out := make([]Result, want)
	for i, e := range rf.entries {
		if e.status == 0 {
			var claims map[string]interface{}
			if err := json.Unmarshal(e.payload, &claims); err != nil {
				return nil, fmt.Errorf("captpu: claims decode: %w", err)
			}
			out[i] = Result{Claims: claims}
		} else {
			err := throttledFromPayload(string(e.payload))
			if t, ok := err.(*ThrottledError); ok {
				c.notePushback(t.RetryAfter)
			}
			out[i] = Result{Err: err}
		}
	}
	return out, nil
}

func (c *Client) fallbackVerify(ctx context.Context, tokens []string) ([]Result, error) {
	out := make([]Result, len(tokens))
	for i, t := range tokens {
		if ctx.Err() != nil {
			return nil, ctx.Err()
		}
		claims, err := c.o.Fallback.VerifySignature(ctx, t)
		if err != nil {
			out[i] = Result{Err: err}
		} else {
			out[i] = Result{Claims: claims}
		}
	}
	return out, nil
}

// controlExchange runs one pre-encoded control frame against the
// first reachable endpoint and returns the parsed response frame.
func (c *Client) controlExchange(frame []byte) (*respFrame, error) {
	var lastErr error
	for _, p := range c.pools {
		w, err := p.get()
		if err != nil {
			lastErr = err
			continue
		}
		rf, err := w.exchange(frame, time.Now().Add(c.o.AttemptTimeout))
		if err != nil {
			w.close()
			lastErr = err
			continue
		}
		p.put(w)
		return rf, nil
	}
	return nil, lastErr
}

// Ping reports whether any endpoint answers a CVB1 ping.
func (c *Client) Ping() bool {
	rf, err := c.controlExchange(encodePing())
	return err == nil && rf.ftype == typePong
}

// Stats fetches one worker's STATS snapshot (counts and timings only).
func (c *Client) Stats() (map[string]interface{}, error) {
	rf, err := c.controlExchange(encodeStatsReq())
	if err != nil {
		return nil, err
	}
	if rf.ftype != typeStatsRsp || len(rf.entries) != 1 {
		return nil, fmt.Errorf("captpu: expected stats response, got type %d", rf.ftype)
	}
	var stats map[string]interface{}
	if err := json.Unmarshal(rf.entries[0].payload, &stats); err != nil {
		return nil, fmt.Errorf("captpu: stats decode: %w", err)
	}
	return stats, nil
}

// PushKeys distributes one key epoch (KEYS push, type 11) to EVERY
// endpoint; returns the acked epoch (all endpoints must ack it).
func (c *Client) PushKeys(jwks map[string]interface{}, epoch int) (int, error) {
	payload, err := json.Marshal(map[string]interface{}{
		"epoch": epoch, "jwks": jwks,
	})
	if err != nil {
		return 0, err
	}
	frame, err := encodeControl(typeKeysPush, payload)
	if err != nil {
		return 0, err
	}
	acked := 0
	for _, p := range c.pools {
		w, err := p.get()
		if err != nil {
			return acked, err
		}
		rf, err := w.exchange(frame, time.Now().Add(c.o.AttemptTimeout))
		if err != nil {
			w.close()
			return acked, err
		}
		p.put(w)
		if rf.ftype != typeKeysAck || len(rf.entries) != 1 || rf.entries[0].status != 0 {
			msg := "keys push refused"
			if len(rf.entries) == 1 {
				msg = string(rf.entries[0].payload)
			}
			return acked, &RemoteVerifyError{Msg: msg}
		}
		var ack struct {
			Epoch int `json:"epoch"`
		}
		if err := json.Unmarshal(rf.entries[0].payload, &ack); err != nil {
			return acked, err
		}
		acked = ack.Epoch
	}
	return acked, nil
}

var _ BatchKeySet = (*Client)(nil)
