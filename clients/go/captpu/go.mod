module github.com/cap-tpu/clients/go/captpu

go 1.15
