// +build !linux,!darwin

package captpu

// Non-unix stub: the shm transport negotiates only where mmap'd
// shared memory exists; everywhere else the client silently keeps the
// socket transport (the same fallback contract a refusing worker
// triggers).

import (
	"errors"
	"time"
)

const (
	ringReq  = 0
	ringResp = 1
)

var errShmUnsupported = errors.New("captpu: shm transport unsupported on this platform")

type shmRegion struct{ path string }

func createShmRegion(path string, reqSize, respSize uint64, gen uint32) (*shmRegion, error) {
	return nil, errShmUnsupported
}

func (r *shmRegion) close(unlink bool) {}

func (r *shmRegion) maxRecord(ring int) uint64 { return 0 }

func (r *shmRegion) writeRecord(ring int, b []byte, deadline time.Time) error {
	return errShmUnsupported
}

func (r *shmRegion) readRecord(ring int, deadline time.Time, alive func() error) ([]byte, error) {
	return nil, errShmUnsupported
}
