// Package captpu is the Go host-language client for the cap_tpu verify
// worker: it exposes cap's KeySet seam (reference: jwt/keyset.go:27-32)
// backed by the batched TPU verify service, so a Go application using
// hashicorp/cap-style verification can route its hot path to the
// accelerator with the pure-Go path staying the default.
//
// The wire protocol is CVB1 (cap_tpu/serve/protocol.py): length-prefixed
// little-endian frames over TCP or a Unix socket. This package speaks it
// natively — no cgo required; libcapclient.so (the C shim) remains
// available for cgo-based hosts.
//
// Redaction stance (reference: oidc/access_token.go:6-19): error strings
// never contain token material, and this package never logs.
package captpu

import (
	"context"
	"encoding/binary"
	"encoding/json"
	"errors"
	"fmt"
	"io"
	"net"
	"regexp"
	"strconv"
	"strings"
	"sync"
	"time"
)

const (
	magic         = 0x31425643 // "CVB1"
	typeVerifyReq = 1
	typeVerifyRsp = 2
	typePing      = 3
	typePong      = 4

	maxEntryBytes = 1 << 20
	maxFrameBytes = 1 << 28
)

// KeySet mirrors cap's verification seam (jwt/keyset.go:27-32): it
// verifies the signature of a compact JWS and returns its claims.
type KeySet interface {
	VerifySignature(ctx context.Context, token string) (map[string]interface{}, error)
}

// BatchKeySet is the batched extension the TPU backend serves.
type BatchKeySet interface {
	KeySet
	// VerifyBatch verifies every token; result i corresponds to
	// tokens[i]. A non-nil error means the whole batch failed
	// (transport); per-token rejections land in Result.Err.
	VerifyBatch(ctx context.Context, tokens []string) ([]Result, error)
}

// Result is one token's verdict.
type Result struct {
	Claims map[string]interface{} // nil when rejected
	Err    error                  // nil when verified
}

// RemoteVerifyError is a per-token rejection from the worker. Its text
// is the worker's error class + message (never the token itself).
type RemoteVerifyError struct{ Msg string }

func (e *RemoteVerifyError) Error() string { return e.Msg }

// ThrottledError is admission pushback: the worker rejected the token
// BEFORE verification because its tenant is over budget. It is NOT a
// verdict about token validity — callers retry after RetryAfter, they
// must not treat it as "invalid" (and the Client never burns a retry
// round or its fallback on it). The wire form is the ordinary
// status-1 entry whose payload head is "ThrottledError" carrying an
// additive "retry_after_ms=<int>" hint.
type ThrottledError struct {
	Msg        string
	RetryAfter time.Duration // 0 when the hint was absent/garbled
}

func (e *ThrottledError) Error() string { return e.Msg }

var retryAfterRe = regexp.MustCompile(`retry_after_ms=(\d{1,9})`)

// throttledFromPayload maps a status-1 payload to its typed error:
// *ThrottledError for admission pushback, *RemoteVerifyError for
// every real rejection.
func throttledFromPayload(payload string) error {
	if !strings.HasPrefix(payload, "ThrottledError") {
		return &RemoteVerifyError{Msg: payload}
	}
	e := &ThrottledError{Msg: payload}
	if m := retryAfterRe.FindStringSubmatch(payload); m != nil {
		if ms, err := strconv.Atoi(m[1]); err == nil {
			e.RetryAfter = time.Duration(ms) * time.Millisecond
		}
	}
	return e
}

// ErrClosed is returned after Close.
var ErrClosed = errors.New("captpu: client closed")

// TPUBatchKeySet is a KeySet backed by a cap_tpu verify worker.
//
// It holds one connection, redialing transparently after transport
// errors (a failed exchange poisons the connection — response bytes
// may be unread — mirroring the native client's handle poisoning).
// Safe for concurrent use; calls serialize on the connection, and the
// worker's AdaptiveBatcher coalesces concurrent callers into device
// batches.
type TPUBatchKeySet struct {
	network string // "tcp" or "unix"
	addr    string

	mu     sync.Mutex
	conn   net.Conn
	closed bool

	// DialTimeout bounds redials (default 10s).
	DialTimeout time.Duration

	// PipelineDepth caps the frames VerifyBatches keeps in flight
	// (default 8 when zero).
	PipelineDepth int
}

// NewTPUBatchKeySet connects to a verify worker. addr is "host:port"
// for TCP or "unix:///path/to.sock" for a Unix socket.
func NewTPUBatchKeySet(addr string) (*TPUBatchKeySet, error) {
	k := &TPUBatchKeySet{network: "tcp", addr: addr, DialTimeout: 10 * time.Second}
	if strings.HasPrefix(addr, "unix://") {
		k.network = "unix"
		k.addr = strings.TrimPrefix(addr, "unix://")
	}
	if err := k.redial(); err != nil {
		return nil, err
	}
	return k, nil
}

func (k *TPUBatchKeySet) redial() error {
	if k.conn != nil {
		k.conn.Close()
		k.conn = nil
	}
	d := net.Dialer{Timeout: k.DialTimeout}
	conn, err := d.Dial(k.network, k.addr)
	if err != nil {
		return fmt.Errorf("captpu: dial %s %s: %w", k.network, k.addr, err)
	}
	if tc, ok := conn.(*net.TCPConn); ok {
		tc.SetNoDelay(true)
	}
	k.conn = conn
	return nil
}

// Close releases the connection. Subsequent calls return ErrClosed.
func (k *TPUBatchKeySet) Close() error {
	k.mu.Lock()
	defer k.mu.Unlock()
	k.closed = true
	if k.conn != nil {
		err := k.conn.Close()
		k.conn = nil
		return err
	}
	return nil
}

// Ping reports worker liveness.
func (k *TPUBatchKeySet) Ping() bool {
	k.mu.Lock()
	defer k.mu.Unlock()
	if k.closed || k.ensureConn() != nil {
		return false
	}
	hdr := make([]byte, 9)
	binary.LittleEndian.PutUint32(hdr[0:4], magic)
	hdr[4] = typePing
	binary.LittleEndian.PutUint32(hdr[5:9], 0)
	if _, err := k.conn.Write(hdr); err != nil {
		k.poison()
		return false
	}
	rsp := make([]byte, 9)
	if _, err := io.ReadFull(k.conn, rsp); err != nil {
		k.poison()
		return false
	}
	if binary.LittleEndian.Uint32(rsp[0:4]) != magic || rsp[4] != typePong {
		k.poison()
		return false
	}
	return true
}

// VerifySignature implements KeySet for a single token.
func (k *TPUBatchKeySet) VerifySignature(ctx context.Context, token string) (map[string]interface{}, error) {
	res, err := k.VerifyBatch(ctx, []string{token})
	if err != nil {
		return nil, err
	}
	if res[0].Err != nil {
		return nil, res[0].Err
	}
	return res[0].Claims, nil
}

// VerifyBatch sends one CVB1 verify frame and decodes the response.
func (k *TPUBatchKeySet) VerifyBatch(ctx context.Context, tokens []string) ([]Result, error) {
	k.mu.Lock()
	defer k.mu.Unlock()
	if k.closed {
		return nil, ErrClosed
	}
	if err := k.ensureConn(); err != nil {
		return nil, err
	}
	if dl, ok := ctx.Deadline(); ok {
		k.conn.SetDeadline(dl)
		defer k.conn.SetDeadline(time.Time{})
	}

	frame, err := encodeRequest(tokens)
	if err != nil {
		return nil, err
	}
	if _, err := k.conn.Write(frame); err != nil {
		k.poison()
		return nil, fmt.Errorf("captpu: send: %w", err)
	}
	res, err := decodeResponse(k.conn, len(tokens))
	if err != nil {
		k.poison()
		return nil, err
	}
	return res, nil
}

// VerifyBatches pipelines several batches over the one connection:
// request frames are written ahead (up to PipelineDepth outstanding)
// by a sender goroutine while responses stream back in request order
// (CVB1 has no request ids — order is the correlation; the worker
// reads eagerly and answers in order). Throughput is then bounded by
// the worker's batcher, not by one round trip per batch. results[i]
// corresponds to batches[i]; a non-nil error poisons the connection.
func (k *TPUBatchKeySet) VerifyBatches(ctx context.Context, batches [][]string) ([][]Result, error) {
	k.mu.Lock()
	defer k.mu.Unlock()
	if k.closed {
		return nil, ErrClosed
	}
	if err := k.ensureConn(); err != nil {
		return nil, err
	}
	if dl, ok := ctx.Deadline(); ok {
		k.conn.SetDeadline(dl)
		defer k.conn.SetDeadline(time.Time{})
	}

	depth := k.PipelineDepth
	if depth <= 0 {
		depth = 8
	}
	// Encode every frame BEFORE the first write: encodeRequest is pure
	// local validation, so an oversized token in a late batch must
	// fail the call up front — not poison a healthy connection after
	// most batches already completed.
	frames := make([][]byte, len(batches))
	for i, toks := range batches {
		frame, err := encodeRequest(toks)
		if err != nil {
			return nil, err
		}
		frames[i] = frame
	}
	conn := k.conn
	slots := make(chan struct{}, depth)
	stop := make(chan struct{})
	defer close(stop)
	var sendErr error
	var sendMu sync.Mutex
	go func() {
		for _, frame := range frames {
			select {
			case slots <- struct{}{}:
			case <-stop:
				return
			}
			if _, err := conn.Write(frame); err != nil {
				sendMu.Lock()
				sendErr = err
				sendMu.Unlock()
				// Unblock the reader: it is mid-ReadFull on a
				// response that will never come.
				conn.Close()
				return
			}
		}
	}()

	out := make([][]Result, 0, len(batches))
	for i := range batches {
		res, err := decodeResponse(k.conn, len(batches[i]))
		if err != nil {
			k.poison()
			sendMu.Lock()
			se := sendErr
			sendMu.Unlock()
			if se != nil {
				return nil, fmt.Errorf("captpu: pipelined send: %w", se)
			}
			return nil, err
		}
		out = append(out, res)
		<-slots
	}
	return out, nil
}

func (k *TPUBatchKeySet) ensureConn() error {
	if k.conn != nil {
		return nil
	}
	return k.redial()
}

// poison drops the connection: after a failed exchange the stream may
// hold unread response bytes, so reuse would misparse later frames.
func (k *TPUBatchKeySet) poison() {
	if k.conn != nil {
		k.conn.Close()
		k.conn = nil
	}
}

// encodeRequest builds a CVB1 verify-request frame.
func encodeRequest(tokens []string) ([]byte, error) {
	size := 9
	for _, t := range tokens {
		if len(t) > maxEntryBytes {
			return nil, fmt.Errorf("captpu: token exceeds %d bytes", maxEntryBytes)
		}
		size += 4 + len(t)
	}
	if size > maxFrameBytes {
		return nil, fmt.Errorf("captpu: frame exceeds %d bytes", maxFrameBytes)
	}
	frame := make([]byte, 0, size)
	var u32 [4]byte
	binary.LittleEndian.PutUint32(u32[:], magic)
	frame = append(frame, u32[:]...)
	frame = append(frame, typeVerifyReq)
	binary.LittleEndian.PutUint32(u32[:], uint32(len(tokens)))
	frame = append(frame, u32[:]...)
	for _, t := range tokens {
		binary.LittleEndian.PutUint32(u32[:], uint32(len(t)))
		frame = append(frame, u32[:]...)
		frame = append(frame, t...)
	}
	return frame, nil
}

// decodeResponse reads one verify-response frame for count tokens.
func decodeResponse(r io.Reader, count int) ([]Result, error) {
	hdr := make([]byte, 9)
	if _, err := io.ReadFull(r, hdr); err != nil {
		return nil, fmt.Errorf("captpu: recv header: %w", err)
	}
	if binary.LittleEndian.Uint32(hdr[0:4]) != magic {
		return nil, errors.New("captpu: bad magic in response")
	}
	if hdr[4] != typeVerifyRsp {
		return nil, fmt.Errorf("captpu: unexpected frame type %d", hdr[4])
	}
	n := binary.LittleEndian.Uint32(hdr[5:9])
	if int(n) != count {
		return nil, fmt.Errorf("captpu: response count %d != request %d", n, count)
	}
	out := make([]Result, count)
	entry := make([]byte, 5)
	total := 0
	for i := 0; i < count; i++ {
		if _, err := io.ReadFull(r, entry); err != nil {
			return nil, fmt.Errorf("captpu: recv entry: %w", err)
		}
		status := entry[0]
		ln := binary.LittleEndian.Uint32(entry[1:5])
		total += int(ln)
		if ln > maxEntryBytes || total > maxFrameBytes {
			return nil, errors.New("captpu: oversized response entry")
		}
		payload := make([]byte, ln)
		if _, err := io.ReadFull(r, payload); err != nil {
			return nil, fmt.Errorf("captpu: recv payload: %w", err)
		}
		if status == 0 {
			var claims map[string]interface{}
			if err := json.Unmarshal(payload, &claims); err != nil {
				return nil, fmt.Errorf("captpu: claims decode: %w", err)
			}
			out[i] = Result{Claims: claims}
		} else {
			out[i] = Result{Err: throttledFromPayload(string(payload))}
		}
	}
	return out, nil
}
