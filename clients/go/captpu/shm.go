// +build linux darwin

package captpu

// Shared-memory ring transport, pure Go (syscall.Mmap — no cgo).
// Region layout and record format mirror cap_tpu/serve/shm_ring.py /
// runtime/native/shm_ring.h byte for byte:
//
//	header (4096 B): magic u64 "CAPSHMR1" | version u32 | gen u32 |
//	    req_off u64 | req_size u64 | resp_off u64 | resp_size u64 |
//	    req_head @64 | req_tail @128 | resp_head @192 | resp_tail @256
//	record: [len u32][gen u32][payload … pad8]; len 0xFFFFFFFF = wrap
//
// The producer writes payload bytes first and publishes with an
// atomic store of head last, so a writer killed mid-record never
// publishes a torn frame. Cursors are 8-byte aligned into the page-
// aligned mapping, so sync/atomic on them is valid on amd64/arm64.

import (
	"encoding/binary"
	"errors"
	"fmt"
	"os"
	"sync/atomic"
	"syscall"
	"time"
	"unsafe"
)

const (
	shmMagic   = 0x31524D4853504143 // "CAPSHMR1"
	shmVersion = 1
	shmHdrSize = 4096
	shmMinRing = 4096
	shmMaxRing = 1 << 30
	shmWrap    = 0xFFFFFFFF

	ringReq  = 0
	ringResp = 1
)

var (
	errShmStale     = errors.New("captpu: shm record from a foreign generation")
	errShmMalformed = errors.New("captpu: shm ring cursor/record malformed")
	errShmTimeout   = errors.New("captpu: shm ring timed out")
	errShmTooLarge  = errors.New("captpu: frame exceeds shm ring capacity")
)

type shmRegion struct {
	path     string
	data     []byte
	gen      uint32
	ringOff  [2]uint64
	ringSize [2]uint64
}

func (r *shmRegion) cursor(off uint64) *uint64 {
	return (*uint64)(unsafe.Pointer(&r.data[off]))
}

func headOff(ring int) uint64 {
	if ring == ringReq {
		return 64
	}
	return 192
}

func tailOff(ring int) uint64 {
	if ring == ringReq {
		return 128
	}
	return 256
}

func pow2InBounds(v uint64) bool {
	return v >= shmMinRing && v <= shmMaxRing && v&(v-1) == 0
}

// createShmRegion creates + initializes a region file (client side).
func createShmRegion(path string, reqSize, respSize uint64, gen uint32) (*shmRegion, error) {
	if !pow2InBounds(reqSize) || !pow2InBounds(respSize) || gen == 0 {
		return nil, errors.New("captpu: bad shm region parameters")
	}
	f, err := os.OpenFile(path, os.O_RDWR|os.O_CREATE|os.O_TRUNC, 0600)
	if err != nil {
		return nil, fmt.Errorf("captpu: shm create: %w", err)
	}
	total := int64(shmHdrSize + reqSize + respSize)
	if err := f.Truncate(total); err != nil {
		f.Close()
		os.Remove(path)
		return nil, fmt.Errorf("captpu: shm truncate: %w", err)
	}
	data, err := syscall.Mmap(int(f.Fd()), 0, int(total),
		syscall.PROT_READ|syscall.PROT_WRITE, syscall.MAP_SHARED)
	f.Close()
	if err != nil {
		os.Remove(path)
		return nil, fmt.Errorf("captpu: shm mmap: %w", err)
	}
	binary.LittleEndian.PutUint32(data[8:], shmVersion)
	binary.LittleEndian.PutUint32(data[12:], gen)
	binary.LittleEndian.PutUint64(data[16:], shmHdrSize)
	binary.LittleEndian.PutUint64(data[24:], reqSize)
	binary.LittleEndian.PutUint64(data[32:], shmHdrSize+reqSize)
	binary.LittleEndian.PutUint64(data[40:], respSize)
	r := &shmRegion{path: path, data: data, gen: gen}
	r.ringOff = [2]uint64{shmHdrSize, shmHdrSize + reqSize}
	r.ringSize = [2]uint64{reqSize, respSize}
	// magic last: a racing reader never sees a half-written header
	atomic.StoreUint64(r.cursor(0), shmMagic)
	return r, nil
}

func (r *shmRegion) close(unlink bool) {
	if r.data != nil {
		syscall.Munmap(r.data)
		r.data = nil
	}
	if unlink {
		os.Remove(r.path)
	}
}

func (r *shmRegion) maxRecord(ring int) uint64 { return r.ringSize[ring] / 2 }

// writeRecord appends one record (blocking while the ring is full).
func (r *shmRegion) writeRecord(ring int, b []byte, deadline time.Time) error {
	size := r.ringSize[ring]
	base := r.ringOff[ring]
	n := uint64(len(b))
	if n > size/2 {
		return errShmTooLarge
	}
	adv := 8 + (n+7)&^uint64(7)
	spins := 0
	for {
		head := atomic.LoadUint64(r.cursor(headOff(ring)))
		tail := atomic.LoadUint64(r.cursor(tailOff(ring)))
		off := head & (size - 1)
		var wrapSkip uint64
		if size-off < adv {
			wrapSkip = size - off
		}
		if size-(head-tail) >= wrapSkip+adv {
			if wrapSkip != 0 {
				binary.LittleEndian.PutUint32(r.data[base+off:], shmWrap)
				binary.LittleEndian.PutUint32(r.data[base+off+4:], r.gen)
				head += wrapSkip
				off = 0
				atomic.StoreUint64(r.cursor(headOff(ring)), head)
			}
			binary.LittleEndian.PutUint32(r.data[base+off:], uint32(n))
			binary.LittleEndian.PutUint32(r.data[base+off+4:], r.gen)
			copy(r.data[base+off+8:base+off+8+n], b)
			atomic.StoreUint64(r.cursor(headOff(ring)), head+adv)
			return nil
		}
		if !deadline.IsZero() && time.Now().After(deadline) {
			return errShmTimeout
		}
		spins++
		if spins < 64 {
			// busy ring: brief yield
			time.Sleep(0)
		} else {
			time.Sleep(200 * time.Microsecond)
		}
	}
}

// readRecord copies the next record's payload out of the ring (the
// producer may reuse the space as soon as the tail moves).
func (r *shmRegion) readRecord(ring int, deadline time.Time, alive func() error) ([]byte, error) {
	size := r.ringSize[ring]
	base := r.ringOff[ring]
	spins := 0
	for {
		head := atomic.LoadUint64(r.cursor(headOff(ring)))
		tail := atomic.LoadUint64(r.cursor(tailOff(ring)))
		if head != tail {
			if head-tail > size || tail&7 != 0 || head-tail < 8 {
				return nil, errShmMalformed
			}
			off := tail & (size - 1)
			recLen := binary.LittleEndian.Uint32(r.data[base+off:])
			recGen := binary.LittleEndian.Uint32(r.data[base+off+4:])
			if recLen == shmWrap {
				if recGen != r.gen {
					return nil, errShmStale
				}
				skip := size - off
				if head-tail < skip {
					return nil, errShmMalformed
				}
				atomic.StoreUint64(r.cursor(tailOff(ring)), tail+skip)
				continue
			}
			if uint64(recLen) > size/2 {
				return nil, errShmMalformed
			}
			adv := 8 + (uint64(recLen)+7)&^uint64(7)
			if adv > size-off || head-tail < adv {
				return nil, errShmMalformed
			}
			if recGen != r.gen {
				return nil, errShmStale
			}
			out := make([]byte, recLen)
			copy(out, r.data[base+off+8:base+off+8+uint64(recLen)])
			atomic.StoreUint64(r.cursor(tailOff(ring)), tail+adv)
			return out, nil
		}
		if alive != nil && spins%256 == 255 {
			if err := alive(); err != nil {
				return nil, err
			}
		}
		if !deadline.IsZero() && time.Now().After(deadline) {
			return nil, errShmTimeout
		}
		spins++
		if spins < 64 {
			time.Sleep(0)
		} else {
			time.Sleep(200 * time.Microsecond)
		}
	}
}
