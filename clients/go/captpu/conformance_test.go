package captpu

// Conformance: (1) a table-driven sweep of EVERY committed golden
// frame — encoders must reproduce the request-direction goldens
// byte-for-byte, decoders must parse the response-direction goldens
// to the pinned values; (2) a live-stub-worker suite that boots the
// repo's Python worker (skipping loudly when python3 is absent) and
// drives the production Client across both transports, including the
// adversarial sig_conformance.json corpus.

import (
	"bufio"
	"bytes"
	"context"
	"encoding/binary"
	"encoding/json"
	"fmt"
	"os"
	"os/exec"
	"path/filepath"
	"strconv"
	"strings"
	"testing"
	"time"
)

type metaResult struct {
	Claims map[string]interface{} `json:"claims"`
	Error  string                 `json:"error"`
}

type fullMeta struct {
	Tokens       []string     `json:"tokens"`
	TraceID      string       `json:"trace_id"`
	ShmPath      string       `json:"shm_path"`
	Results      []metaResult `json:"results"`
	PushResults  []metaResult `json:"push_results"`
	PushRetryMS  int          `json:"push_retry_after_ms"`
}

func loadMeta(t *testing.T) fullMeta {
	t.Helper()
	var m fullMeta
	if err := json.Unmarshal(readGolden(t, "meta.json"), &m); err != nil {
		t.Fatal(err)
	}
	return m
}

// controlPayload extracts the single entry payload out of a committed
// one-entry control frame (types 11/13/15): 9 header + 4 len bytes.
// Re-encoding it through encodeControl must reproduce the golden —
// this pins the frame codec without re-deriving Python's JSON number
// formatting in Go.
func controlPayload(t *testing.T, frame []byte) []byte {
	t.Helper()
	if len(frame) < 13 {
		t.Fatalf("control frame too short: %d bytes", len(frame))
	}
	ln := binary.LittleEndian.Uint32(frame[9:13])
	if len(frame) != 13+int(ln)+4 {
		t.Fatalf("control frame length mismatch: %d vs %d", len(frame), 13+int(ln)+4)
	}
	return frame[13 : 13+int(ln)]
}

func TestGoldenFrameSweepEncoders(t *testing.T) {
	meta := loadMeta(t)
	cases := []struct {
		golden string
		build  func() ([]byte, error)
	}{
		{"request.bin", func() ([]byte, error) {
			return encodeRequestEx(meta.Tokens, false, "")
		}},
		{"request_crc.bin", func() ([]byte, error) {
			return encodeRequestEx(meta.Tokens, true, "")
		}},
		{"request_trace.bin", func() ([]byte, error) {
			return encodeRequestEx(meta.Tokens, false, meta.TraceID)
		}},
		{"ping.bin", func() ([]byte, error) { return encodePing(), nil }},
		{"stats_request.bin", func() ([]byte, error) { return encodeStatsReq(), nil }},
		{"keys_push.bin", func() ([]byte, error) {
			return encodeControl(typeKeysPush,
				controlPayload(t, readGolden(t, "keys_push.bin")))
		}},
		{"peer_fill.bin", func() ([]byte, error) {
			return encodeControl(typePeerFill,
				controlPayload(t, readGolden(t, "peer_fill.bin")))
		}},
		{"shm_attach.bin", func() ([]byte, error) {
			// the exact payload string dialWire builds
			payload := []byte(`{"op":"attach","path":"` + meta.ShmPath + `","version":1}`)
			return encodeControl(typeShmAttach, payload)
		}},
	}
	for _, tc := range cases {
		got, err := tc.build()
		if err != nil {
			t.Fatalf("%s: %v", tc.golden, err)
		}
		want := readGolden(t, tc.golden)
		if !bytes.Equal(got, want) {
			t.Errorf("%s: encoder drifted from the committed golden\n got %x\nwant %x",
				tc.golden, got, want)
		}
	}
}

func TestGoldenFrameSweepDecoders(t *testing.T) {
	meta := loadMeta(t)
	decode := func(name string) *respFrame {
		t.Helper()
		rf, err := readFrame(bufio.NewReader(bytes.NewReader(readGolden(t, name))))
		if err != nil {
			t.Fatalf("%s: %v", name, err)
		}
		return rf
	}

	checkVerify := func(name string, rf *respFrame) {
		t.Helper()
		if len(rf.entries) != len(meta.Results) {
			t.Fatalf("%s: %d entries, want %d", name, len(rf.entries), len(meta.Results))
		}
		for i, want := range meta.Results {
			e := rf.entries[i]
			if want.Error != "" {
				if e.status != 1 || string(e.payload) != want.Error {
					t.Fatalf("%s entry %d: status %d payload %q, want error %q",
						name, i, e.status, e.payload, want.Error)
				}
				continue
			}
			if e.status != 0 {
				t.Fatalf("%s entry %d: unexpected reject", name, i)
			}
			var claims map[string]interface{}
			if err := json.Unmarshal(e.payload, &claims); err != nil {
				t.Fatalf("%s entry %d: %v", name, i, err)
			}
		}
	}

	if rf := decode("response.bin"); rf.ftype != typeVerifyRsp {
		t.Fatalf("response.bin: type %d", rf.ftype)
	} else {
		checkVerify("response.bin", rf)
	}
	if rf := decode("response_crc.bin"); rf.ftype != typeVerifyRspCRC {
		t.Fatalf("response_crc.bin: type %d", rf.ftype)
	} else {
		checkVerify("response_crc.bin", rf)
	}
	rf := decode("response_trace.bin")
	if rf.ftype != typeVerifyRspTr || rf.trace != meta.TraceID {
		t.Fatalf("response_trace.bin: type %d trace %q", rf.ftype, rf.trace)
	}
	checkVerify("response_trace.bin", rf)

	if rf := decode("pong.bin"); rf.ftype != typePong {
		t.Fatalf("pong.bin: type %d", rf.ftype)
	}
	rf = decode("stats_response.bin")
	if rf.ftype != typeStatsRsp || len(rf.entries) != 1 {
		t.Fatalf("stats_response.bin: type %d", rf.ftype)
	}
	var stats map[string]interface{}
	if err := json.Unmarshal(rf.entries[0].payload, &stats); err != nil {
		t.Fatal(err)
	}
	if _, ok := stats["pid"]; !ok {
		t.Fatal("stats_response.bin: no pid field")
	}
	rf = decode("keys_ack.bin")
	if rf.ftype != typeKeysAck || rf.entries[0].status != 0 {
		t.Fatalf("keys_ack.bin: type %d status %d", rf.ftype, rf.entries[0].status)
	}
	var ack struct {
		Epoch int `json:"epoch"`
	}
	if err := json.Unmarshal(rf.entries[0].payload, &ack); err != nil || ack.Epoch != 3 {
		t.Fatalf("keys_ack.bin: epoch %d err %v", ack.Epoch, err)
	}
	rf = decode("peer_ack.bin")
	if rf.ftype != typePeerAck || rf.entries[0].status != 0 {
		t.Fatalf("peer_ack.bin: type %d", rf.ftype)
	}
	var peer struct {
		Imported int `json:"imported"`
	}
	if err := json.Unmarshal(rf.entries[0].payload, &peer); err != nil || peer.Imported != 1 {
		t.Fatalf("peer_ack.bin: imported %d err %v", peer.Imported, err)
	}
	rf = decode("shm_ack.bin")
	if rf.ftype != typeShmAck || rf.entries[0].status != 0 {
		t.Fatalf("shm_ack.bin: type %d", rf.ftype)
	}
	var sa struct {
		Transport string `json:"transport"`
	}
	if err := json.Unmarshal(rf.entries[0].payload, &sa); err != nil || sa.Transport != "shm" {
		t.Fatalf("shm_ack.bin: transport %q err %v", sa.Transport, err)
	}
}

// TestPushbackGolden pins the r20 admission-pushback vector: a mixed
// verified/throttled response must decode to a typed *ThrottledError
// with the retry_after_ms hint parsed — on the plain AND checksummed
// frame forms. (The hint rides the ordinary status-1 payload, so a
// stale client sees one more RemoteVerifyError and nothing breaks.)
func TestPushbackGolden(t *testing.T) {
	meta := loadMeta(t)
	if len(meta.PushResults) == 0 {
		t.Fatal("meta.json carries no push_results (regenerate: python tools/gen_go_golden.py)")
	}
	for _, name := range []string{"response_push.bin", "response_push_crc.bin"} {
		rf, err := readFrame(bufio.NewReader(bytes.NewReader(readGolden(t, name))))
		if err != nil {
			t.Fatalf("%s: %v", name, err)
		}
		if len(rf.entries) != len(meta.PushResults) {
			t.Fatalf("%s: %d entries, want %d", name, len(rf.entries), len(meta.PushResults))
		}
		for i, want := range meta.PushResults {
			e := rf.entries[i]
			if want.Error == "" {
				if e.status != 0 {
					t.Fatalf("%s entry %d: unexpected reject", name, i)
				}
				continue
			}
			if e.status != 1 || string(e.payload) != want.Error {
				t.Fatalf("%s entry %d: status %d payload %q, want %q",
					name, i, e.status, e.payload, want.Error)
			}
			err := throttledFromPayload(string(e.payload))
			te, ok := err.(*ThrottledError)
			if !ok {
				t.Fatalf("%s entry %d: decoded %T, want *ThrottledError", name, i, err)
			}
			wantDur := time.Duration(meta.PushRetryMS) * time.Millisecond
			if te.RetryAfter != wantDur {
				t.Fatalf("%s entry %d: RetryAfter %v, want %v", name, i, te.RetryAfter, wantDur)
			}
		}
	}
	// a non-throttled payload must stay a plain RemoteVerifyError
	if _, ok := throttledFromPayload("InvalidSignatureError: nope").(*RemoteVerifyError); !ok {
		t.Fatal("plain rejection decoded as ThrottledError")
	}
}

func TestCorruptChecksummedFrameDetected(t *testing.T) {
	for _, name := range []string{"response_crc.bin", "response_trace.bin",
		"keys_ack.bin", "peer_ack.bin", "shm_ack.bin"} {
		frame := append([]byte(nil), readGolden(t, name)...)
		frame[10] ^= 0x01 // flip one payload-region byte
		_, err := readFrame(bufio.NewReader(bytes.NewReader(frame)))
		if err == nil {
			t.Fatalf("%s: corrupted frame accepted", name)
		}
	}
}

// ---------------------------------------------------------------------------
// live stub worker (needs python3; skips loudly otherwise)
// ---------------------------------------------------------------------------

func repoRoot(t *testing.T) string {
	t.Helper()
	wd, err := os.Getwd()
	if err != nil {
		t.Fatal(err)
	}
	root := filepath.Clean(filepath.Join(wd, "..", "..", ".."))
	if _, err := os.Stat(filepath.Join(root, "cap_tpu", "serve", "protocol.py")); err != nil {
		t.Skipf("SKIP live-worker suite: repo root not found from %s", wd)
	}
	return root
}

func startStubWorker(t *testing.T, extraArgs ...string) (string, func()) {
	t.Helper()
	python, err := exec.LookPath("python3")
	if err != nil {
		t.Skip("SKIP live-worker suite: no python3 on PATH " +
			"(the golden sweep above still pins the framing)")
	}
	root := repoRoot(t)
	args := append([]string{"-m", "cap_tpu.fleet.worker_main",
		"--keyset", "stub:raw=1", "--obs-port", "-1"}, extraArgs...)
	cmd := exec.Command(python, args...)
	cmd.Dir = root
	cmd.Env = append(os.Environ(), "JAX_PLATFORMS=cpu")
	stdout, err := cmd.StdoutPipe()
	if err != nil {
		t.Fatal(err)
	}
	cmd.Stderr = os.Stderr
	if err := cmd.Start(); err != nil {
		t.Fatal(err)
	}
	stop := func() {
		cmd.Process.Kill()
		cmd.Wait()
	}
	ready := make(chan string, 1)
	go func() {
		sc := bufio.NewScanner(stdout)
		for sc.Scan() {
			line := sc.Text()
			if strings.HasPrefix(line, "CAP_FLEET_READY") {
				ready <- line
				break
			}
		}
		close(ready)
	}()
	select {
	case line, ok := <-ready:
		if !ok {
			stop()
			t.Fatal("worker died before its ready line")
		}
		port := ""
		for _, f := range strings.Fields(line) {
			if strings.HasPrefix(f, "port=") {
				port = strings.TrimPrefix(f, "port=")
			}
		}
		if _, err := strconv.Atoi(port); err != nil {
			stop()
			t.Fatalf("bad ready line %q", line)
		}
		return "127.0.0.1:" + port, stop
	case <-time.After(60 * time.Second):
		stop()
		t.Fatal("worker ready-line timeout")
		return "", nil
	}
}

func TestLiveClientAgainstStubWorker(t *testing.T) {
	addr, stop := startStubWorker(t)
	defer stop()
	for _, crc := range []bool{false, true} {
		client, err := NewClient(Options{Addrs: []string{addr}, CRC: crc})
		if err != nil {
			t.Fatal(err)
		}
		res, err := client.VerifyBatch(context.Background(),
			[]string{"go-live-1.ok", "go-live-2.bad", "go-live-3.ok"})
		if err != nil {
			t.Fatalf("crc=%v: %v", crc, err)
		}
		if res[0].Err != nil || res[2].Err != nil || res[1].Err == nil {
			t.Fatalf("crc=%v: wrong verdicts %+v", crc, res)
		}
		if !client.Ping() {
			t.Fatalf("crc=%v: ping failed", crc)
		}
		stats, err := client.Stats()
		if err != nil || stats["serve_chain"] == nil {
			t.Fatalf("crc=%v: stats %v err %v", crc, stats, err)
		}
		if epoch, err := client.PushKeys(map[string]interface{}{
			"keys": []interface{}{}}, 9); err != nil || epoch != 9 {
			t.Fatalf("crc=%v: push keys epoch %d err %v", crc, epoch, err)
		}
		client.Close()
	}
}

func TestLiveSigConformanceCorpus(t *testing.T) {
	// Every adversarial signature-encoding vector must come back as a
	// DECODABLE class+message rejection through the Go client — never
	// a transport error, never a mangled frame. (Verdict parity with
	// real engines is pinned Python-side in tests/test_conformance.py;
	// the stub rejects everything without an .ok suffix.)
	var corpus struct {
		Vectors []struct {
			Name  string `json:"name"`
			Token string `json:"token"`
		} `json:"vectors"`
	}
	if err := json.Unmarshal(readGolden(t, "sig_conformance.json"), &corpus); err != nil {
		t.Fatal(err)
	}
	if len(corpus.Vectors) < 20 {
		t.Fatalf("suspiciously small corpus: %d vectors", len(corpus.Vectors))
	}
	addr, stop := startStubWorker(t)
	defer stop()
	client, err := NewClient(Options{Addrs: []string{addr}, CRC: true})
	if err != nil {
		t.Fatal(err)
	}
	defer client.Close()
	tokens := make([]string, len(corpus.Vectors))
	for i, v := range corpus.Vectors {
		tokens[i] = v.Token
	}
	res, err := client.VerifyBatch(context.Background(), tokens)
	if err != nil {
		t.Fatal(err)
	}
	for i, r := range res {
		if r.Err == nil {
			t.Fatalf("%s: stub accepted an adversarial vector", corpus.Vectors[i].Name)
		}
		if !strings.Contains(r.Err.Error(), ":") {
			t.Fatalf("%s: rejection %q has no class prefix", corpus.Vectors[i].Name, r.Err)
		}
	}
}

func TestLiveShmTransport(t *testing.T) {
	addr, stop := startStubWorker(t, "--transport", "shm")
	defer stop()
	client, err := NewClient(Options{Addrs: []string{addr}, Transport: "shm"})
	if err != nil {
		t.Fatalf("shm attach against a --transport shm worker failed: %v", err)
	}
	defer client.Close()
	if tr, err := client.Transport(); err != nil || tr != "shm" {
		t.Fatalf("transport %q err %v", tr, err)
	}
	res, err := client.VerifyBatch(context.Background(),
		[]string{"shm-go-1.ok", "shm-go-2.bad"})
	if err != nil {
		t.Fatal(err)
	}
	if res[0].Err != nil || res[1].Err == nil {
		t.Fatalf("wrong verdicts over shm: %+v", res)
	}
	if !client.Ping() {
		t.Fatal("ping over shm failed")
	}
	stats, err := client.Stats()
	if err != nil {
		t.Fatal(err)
	}
	if tr, _ := stats["transport"].(string); tr != "shm" {
		t.Fatalf("worker reports transport %q", tr)
	}
	// sustained pipelined load over the rings
	for i := 0; i < 50; i++ {
		toks := make([]string, 16)
		for j := range toks {
			toks[j] = fmt.Sprintf("shm-go-%d-%d.ok", i, j)
		}
		res, err := client.VerifyBatch(context.Background(), toks)
		if err != nil {
			t.Fatalf("round %d: %v", i, err)
		}
		for _, r := range res {
			if r.Err != nil {
				t.Fatalf("round %d: unexpected reject", i)
			}
		}
	}
}

func TestLiveShmRefusalFallsBackToSocket(t *testing.T) {
	addr, stop := startStubWorker(t) // transport=socket: attach refused
	defer stop()
	client, err := NewClient(Options{Addrs: []string{addr}, Transport: "auto"})
	if err != nil {
		t.Fatal(err)
	}
	defer client.Close()
	if tr, err := client.Transport(); err != nil || tr != "socket" {
		t.Fatalf("transport %q err %v (refusal must keep the socket)", tr, err)
	}
	res, err := client.VerifyBatch(context.Background(), []string{"fb.ok"})
	if err != nil || res[0].Err != nil {
		t.Fatalf("socket fallback broken: %v %+v", err, res)
	}
}

type stubFallback struct{}

func (stubFallback) VerifySignature(ctx context.Context, token string) (map[string]interface{}, error) {
	return map[string]interface{}{"sub": token, "via": "fallback"}, nil
}

func TestLiveFallbackAfterWorkerDeath(t *testing.T) {
	addr, stop := startStubWorker(t)
	client, err := NewClient(Options{
		Addrs:          []string{addr},
		AttemptTimeout: 500 * time.Millisecond,
		Retries:        1,
		Backoff:        10 * time.Millisecond,
		HedgeAfter:     -1,
		Fallback:       stubFallback{},
	})
	if err != nil {
		stop()
		t.Fatal(err)
	}
	defer client.Close()
	stop() // kill the worker: every endpoint round must now fail
	res, err := client.VerifyBatch(context.Background(), []string{"dead.ok"})
	if err != nil {
		t.Fatalf("terminal fallback did not engage: %v", err)
	}
	if res[0].Err != nil || res[0].Claims["via"] != "fallback" {
		t.Fatalf("fallback verdict wrong: %+v", res[0])
	}
}
