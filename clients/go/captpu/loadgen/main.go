// Command loadgen is the Go-driver closed loop tools/bench_serve.py
// shells out to for the `go_client_vps` number: N goroutines, each
// pipelining verify batches through a captpu.Client against a live
// worker, printing one JSON line with the sustained rate.
//
//	go run ./loadgen -addr 127.0.0.1:PORT -seconds 5 -batch 64 \
//	    -conns 4 -transport auto
package main

import (
	"context"
	"encoding/json"
	"flag"
	"fmt"
	"os"
	"sync"
	"sync/atomic"
	"time"

	captpu "github.com/cap-tpu/clients/go/captpu"
)

func main() {
	addr := flag.String("addr", "127.0.0.1:9000", "worker host:port or unix:///path")
	seconds := flag.Float64("seconds", 5, "measurement window")
	batch := flag.Int("batch", 64, "tokens per verify frame")
	conns := flag.Int("conns", 4, "concurrent drivers")
	transport := flag.String("transport", "auto", "auto | socket | shm")
	crc := flag.Bool("crc", false, "checksummed frames (types 7/8)")
	flag.Parse()

	client, err := captpu.NewClient(captpu.Options{
		Addrs:     []string{*addr},
		Transport: *transport,
		CRC:       *crc,
		PoolSize:  *conns,
	})
	if err != nil {
		fmt.Fprintf(os.Stderr, "loadgen: %v\n", err)
		os.Exit(1)
	}
	defer client.Close()
	tr, _ := client.Transport()

	tokens := make([]string, *batch)
	for i := range tokens {
		tokens[i] = fmt.Sprintf("eyJhbGciOiJFUzI1NiJ9.go-load-%04d.ok", i)
	}
	var total int64
	var errs int64
	deadline := time.Now().Add(time.Duration(*seconds * float64(time.Second)))
	ctx := context.Background()
	var wg sync.WaitGroup
	start := time.Now()
	for c := 0; c < *conns; c++ {
		wg.Add(1)
		go func() {
			defer wg.Done()
			for time.Now().Before(deadline) {
				res, err := client.VerifyBatch(ctx, tokens)
				if err != nil {
					atomic.AddInt64(&errs, 1)
					return
				}
				atomic.AddInt64(&total, int64(len(res)))
			}
		}()
	}
	wg.Wait()
	elapsed := time.Since(start).Seconds()
	out := map[string]interface{}{
		"go_client_vps": float64(atomic.LoadInt64(&total)) / elapsed,
		"tokens":        atomic.LoadInt64(&total),
		"seconds":       elapsed,
		"transport":     tr,
		"errors":        atomic.LoadInt64(&errs),
	}
	b, _ := json.Marshal(out)
	fmt.Println(string(b))
	if atomic.LoadInt64(&errs) > 0 {
		os.Exit(1)
	}
}
