#!/usr/bin/env python3
"""CLI example: local-listener OIDC login.

Analog of the reference's oidc/examples/cli (main.go:24-307):
environment-configured (OIDC_CLIENT_ID / OIDC_CLIENT_SECRET /
OIDC_ISSUER / OIDC_PORT) authorization-code login with optional
--implicit / --implicit-access-token / --pkce / --max-age / --scopes
flags. Starts a local callback listener, prints the authorize URL for
the browser, and waits for the callback (or SIGINT / timeout).

``--demo`` runs fully headless: it starts the in-process TestProvider
IdP, drives the authorize endpoint itself, and prints the verified
token — runnable documentation for the whole flow.

Usage:
    python examples/cli.py --demo [--pkce | --implicit]
    OIDC_ISSUER=... OIDC_CLIENT_ID=... python examples/cli.py
"""

import argparse
import json
import os
import sys
import threading
from wsgiref.simple_server import make_server

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cap_tpu.oidc import Config, Provider, Request, S256Verifier  # noqa: E402
from cap_tpu.oidc.callback import SingleRequestReader, auth_code, implicit  # noqa: E402

# Real success page, like the reference CLI's responses.go: the browser
# tab a human lands on after login deserves more than a bare <h1>.
SUCCESS_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
  <meta charset="UTF-8">
  <meta name="viewport" content="width=device-width, initial-scale=1">
  <title>Signed in</title>
  <style>
    body { margin: 0; font: 15px/1.5 system-ui, sans-serif;
           background: #f4f6f8; color: #21262c; }
    main { max-width: 26rem; margin: 18vh auto 0; background: #fff;
           border: 1px solid #d7dde3; border-radius: 6px;
           padding: 2rem 2.25rem; text-align: center; }
    .tick { width: 3rem; height: 3rem; margin: 0 auto 1rem;
            border-radius: 50%; background: #e6f4ea; color: #1a7f37;
            font-size: 1.8rem; line-height: 3rem; }
    h1 { font-size: 1.2rem; margin: 0 0 .4rem; }
    p { margin: 0; color: #57606a; }
  </style>
</head>
<body>
  <main>
    <div class="tick">&#10003;</div>
    <h1>Authentication succeeded</h1>
    <p>You are signed in. You can close this window and return to the
       command line.</p>
  </main>
</body>
</html>"""

ERROR_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
  <meta charset="UTF-8">
  <title>Sign-in failed</title>
  <style>
    body { margin: 0; font: 15px/1.5 system-ui, sans-serif;
           background: #f4f6f8; color: #21262c; }
    main { max-width: 26rem; margin: 18vh auto 0; background: #fff;
           border: 1px solid #ecc8c8; border-radius: 6px;
           padding: 2rem 2.25rem; text-align: center; }
    h1 { font-size: 1.2rem; margin: 0 0 .4rem; color: #99242d; }
    p { margin: 0; color: #57606a; }
  </style>
</head>
<body>
  <main>
    <h1>Authentication failed</h1>
    <p>%s</p>
  </main>
</body>
</html>"""


def printable_token(token) -> dict:
    """Unwrap the redacted token fields for terminal output.

    The reference CLI does the same (its Token redacts IDToken/
    AccessToken/RefreshToken in JSON, examples/cli/main.go:372-381) —
    an interactive login tool is the one place the operator explicitly
    asked to SEE the credentials.
    """
    return {
        "id_token": token.id_token().reveal(),
        "access_token": token.access_token().reveal(),
        "refresh_token": token.refresh_token().reveal(),
        "expiry": token.expiry(),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--implicit", action="store_true")
    ap.add_argument("--implicit-access-token", action="store_true")
    ap.add_argument("--pkce", action="store_true")
    ap.add_argument("--max-age", type=int, default=None)
    ap.add_argument("--scopes", default="")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("OIDC_PORT", "0")))
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--demo", action="store_true",
                    help="run against an in-process TestProvider, headless")
    args = ap.parse_args()

    idp = None
    if args.demo:
        from cap_tpu.oidc.testing import TestProvider

        idp = TestProvider().start()
        issuer, client_id, client_secret = (
            idp.issuer(), idp.client_id, idp.client_secret)
        ca = idp.ca_cert()
    else:
        issuer = os.environ.get("OIDC_ISSUER", "")
        client_id = os.environ.get("OIDC_CLIENT_ID", "")
        client_secret = os.environ.get("OIDC_CLIENT_SECRET", "")
        ca = os.environ.get("OIDC_CA_PEM", "")
        if not issuer or not client_id:
            print("set OIDC_ISSUER and OIDC_CLIENT_ID (or use --demo)")
            return 2

    done = threading.Event()
    outcome = {}

    def success(state, token, environ):
        outcome["token"] = token
        done.set()
        return (200, [("Content-Type", "text/html")], SUCCESS_HTML)

    def error(state, resp, err, environ):
        outcome["error"] = resp.error if resp else str(err)
        done.set()
        # the error string is attacker-influencable (the ?error= query
        # param reaches here unvalidated) — escape it, and never tokens
        import html

        return (401, [("Content-Type", "text/html")],
                ERROR_HTML % html.escape(outcome["error"]))

    holder = {}
    server = make_server("127.0.0.1", args.port,
                         lambda e, s: holder["app"](e, s))
    server.RequestHandlerClass.log_message = lambda *a: None
    callback_url = f"http://127.0.0.1:{server.server_address[1]}/callback"

    config = Config(
        issuer=issuer, client_id=client_id, client_secret=client_secret,
        supported_signing_algs=["ES256", "RS256"],
        allowed_redirect_urls=[callback_url],
        provider_ca=ca or None,
        scopes=[s for s in args.scopes.split(",") if s],
    )
    provider = Provider(config)

    request = Request(
        300, callback_url,
        implicit_flow=args.implicit,
        implicit_access_token=args.implicit_access_token,
        pkce_verifier=S256Verifier() if args.pkce else None,
        max_age=args.max_age,
    )
    reader = SingleRequestReader(request)
    if args.implicit or args.implicit_access_token:
        holder["app"] = implicit(provider, reader, success, error)
    else:
        holder["app"] = auth_code(provider, reader, success, error)

    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = provider.auth_url(request)
    print(f"Open the following URL in your browser:\n\n  {url}\n")

    if args.demo:
        # headless: drive the IdP ourselves (it redirects/form-posts back)
        import re
        import urllib.request
        from urllib.parse import urlencode

        from cap_tpu.utils import http as _http

        idp.set_expected_auth_nonce(request.nonce())
        status, body, _ = _http.get(url, _http.ssl_context_for_ca(ca))
        if args.implicit or args.implicit_access_token:
            fields = dict(re.findall(
                r'name="([^"]+)" value="([^"]+)"', body.decode()))
            post = urllib.request.Request(
                callback_url, data=urlencode(fields).encode(), method="POST")
            post.add_header("Content-Type",
                            "application/x-www-form-urlencoded")
            urllib.request.urlopen(post).read()

    if not done.wait(args.timeout):
        print("timed out waiting for the callback")
        return 1
    server.shutdown()
    try:
        if "error" in outcome:
            print(f"login failed: {outcome['error']}")
            return 1
        token = outcome["token"]
        print("token:")
        print(json.dumps(printable_token(token), indent=2))
        print("id_token claims:")
        print(json.dumps(token.id_token().claims(), indent=2))
        if token.valid():
            ts = token.static_token_source()
            sub = token.id_token().claims()["sub"]
            print("userinfo:")
            print(json.dumps(provider.userinfo(ts, sub), indent=2))
        return 0
    finally:
        if idp is not None:
            idp.stop()


if __name__ == "__main__":
    sys.exit(main())
