#!/usr/bin/env python3
"""CLI example: local-listener OIDC login.

Analog of the reference's oidc/examples/cli (main.go:24-307):
environment-configured (OIDC_CLIENT_ID / OIDC_CLIENT_SECRET /
OIDC_ISSUER / OIDC_PORT) authorization-code login with optional
--implicit / --implicit-access-token / --pkce / --max-age / --scopes
flags. Starts a local callback listener, prints the authorize URL for
the browser, and waits for the callback (or SIGINT / timeout).

``--demo`` runs fully headless: it starts the in-process TestProvider
IdP, drives the authorize endpoint itself, and prints the verified
token — runnable documentation for the whole flow.

Usage:
    python examples/cli.py --demo [--pkce | --implicit]
    OIDC_ISSUER=... OIDC_CLIENT_ID=... python examples/cli.py
"""

import argparse
import json
import os
import sys
import threading
from wsgiref.simple_server import make_server

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cap_tpu.oidc import Config, Provider, Request, S256Verifier  # noqa: E402
from cap_tpu.oidc.callback import SingleRequestReader, auth_code, implicit  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--implicit", action="store_true")
    ap.add_argument("--implicit-access-token", action="store_true")
    ap.add_argument("--pkce", action="store_true")
    ap.add_argument("--max-age", type=int, default=None)
    ap.add_argument("--scopes", default="")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("OIDC_PORT", "0")))
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--demo", action="store_true",
                    help="run against an in-process TestProvider, headless")
    args = ap.parse_args()

    idp = None
    if args.demo:
        from cap_tpu.oidc.testing import TestProvider

        idp = TestProvider().start()
        issuer, client_id, client_secret = (
            idp.issuer(), idp.client_id, idp.client_secret)
        ca = idp.ca_cert()
    else:
        issuer = os.environ.get("OIDC_ISSUER", "")
        client_id = os.environ.get("OIDC_CLIENT_ID", "")
        client_secret = os.environ.get("OIDC_CLIENT_SECRET", "")
        ca = os.environ.get("OIDC_CA_PEM", "")
        if not issuer or not client_id:
            print("set OIDC_ISSUER and OIDC_CLIENT_ID (or use --demo)")
            return 2

    done = threading.Event()
    outcome = {}

    def success(state, token, environ):
        outcome["token"] = token
        done.set()
        return (200, [("Content-Type", "text/html")],
                "<h1>Login successful!</h1>You may close this window.")

    def error(state, resp, err, environ):
        outcome["error"] = resp.error if resp else str(err)
        done.set()
        return (401, [("Content-Type", "text/plain")],
                f"login failed: {outcome['error']}")

    holder = {}
    server = make_server("127.0.0.1", args.port,
                         lambda e, s: holder["app"](e, s))
    server.RequestHandlerClass.log_message = lambda *a: None
    callback_url = f"http://127.0.0.1:{server.server_address[1]}/callback"

    config = Config(
        issuer=issuer, client_id=client_id, client_secret=client_secret,
        supported_signing_algs=["ES256", "RS256"],
        allowed_redirect_urls=[callback_url],
        provider_ca=ca or None,
        scopes=[s for s in args.scopes.split(",") if s],
    )
    provider = Provider(config)

    request = Request(
        300, callback_url,
        implicit_flow=args.implicit,
        implicit_access_token=args.implicit_access_token,
        pkce_verifier=S256Verifier() if args.pkce else None,
        max_age=args.max_age,
    )
    reader = SingleRequestReader(request)
    if args.implicit or args.implicit_access_token:
        holder["app"] = implicit(provider, reader, success, error)
    else:
        holder["app"] = auth_code(provider, reader, success, error)

    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = provider.auth_url(request)
    print(f"Open the following URL in your browser:\n\n  {url}\n")

    if args.demo:
        # headless: drive the IdP ourselves (it redirects/form-posts back)
        import re
        import urllib.request
        from urllib.parse import urlencode

        from cap_tpu.utils import http as _http

        idp.set_expected_auth_nonce(request.nonce())
        status, body, _ = _http.get(url, _http.ssl_context_for_ca(ca))
        if args.implicit or args.implicit_access_token:
            fields = dict(re.findall(
                r'name="([^"]+)" value="([^"]+)"', body.decode()))
            post = urllib.request.Request(
                callback_url, data=urlencode(fields).encode(), method="POST")
            post.add_header("Content-Type",
                            "application/x-www-form-urlencoded")
            urllib.request.urlopen(post).read()

    if not done.wait(args.timeout):
        print("timed out waiting for the callback")
        return 1
    server.shutdown()
    try:
        if "error" in outcome:
            print(f"login failed: {outcome['error']}")
            return 1
        token = outcome["token"]
        print("id_token claims:")
        print(json.dumps(token.id_token().claims(), indent=2))
        if token.valid():
            ts = token.static_token_source()
            sub = token.id_token().claims()["sub"]
            print("userinfo:")
            print(json.dumps(provider.userinfo(ts, sub), indent=2))
        return 0
    finally:
        if idp is not None:
            idp.stop()


if __name__ == "__main__":
    sys.exit(main())
