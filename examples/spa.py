#!/usr/bin/env python3
"""SPA example: a small web app with /login, /callback and /success.

Analog of the reference's oidc/examples/spa (main.go:62-174 +
request_cache.go): a WSGI app holding a mutexed in-memory request
cache — reads delete expired entries; a successful callback attaches
the token to the cached request for /success to render.

``--demo`` starts an in-process TestProvider and drives one login
headlessly.
"""

import argparse
import json
import os
import sys
import threading
import time
from urllib.parse import parse_qs
from wsgiref.simple_server import make_server

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cap_tpu.errors import NotFoundError  # noqa: E402
from cap_tpu.oidc import Config, Provider, Request  # noqa: E402
from cap_tpu.oidc.callback import RequestReader, auth_code  # noqa: E402


class RequestCache(RequestReader):
    """Mutexed in-memory request cache (spa/request_cache.go:16-70)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_state = {}
        self._tokens = {}

    def add(self, request: Request) -> None:
        with self._lock:
            self._by_state[request.state()] = request

    def read(self, state: str):
        with self._lock:
            req = self._by_state.get(state)
            if req is None:
                return None
            if req.is_expired():
                del self._by_state[state]
                return None
            return req

    def set_token(self, state: str, token) -> None:
        with self._lock:
            if state not in self._by_state:
                raise NotFoundError(f"no request for state {state}")
            self._tokens[state] = token

    def token(self, state: str):
        with self._lock:
            return self._tokens.get(state)


def build_app(provider: Provider, cache: RequestCache, callback_url: str):
    def success(state, token, environ):
        cache.set_token(state, token)
        return (302, [("Location", f"/success?state={state}")], b"")

    def error(state, resp, err, environ):
        label = resp.error if resp else str(err)
        return (401, [("Content-Type", "text/plain")], f"login failed: {label}")

    callback_app = auth_code(provider, cache, success, error)

    def app(environ, start_response):
        path = environ.get("PATH_INFO", "/")
        if path == "/login":
            request = Request(300, callback_url)
            cache.add(request)
            start_response("302 Found",
                           [("Location", provider.auth_url(request))])
            return [b""]
        if path == "/callback":
            return callback_app(environ, start_response)
        if path == "/success":
            q = parse_qs(environ.get("QUERY_STRING", ""))
            state = (q.get("state") or [""])[0]
            token = cache.token(state)
            if token is None:
                start_response("404 Not Found", [])
                return [b"no login for that state"]
            claims = token.id_token().claims()
            start_response("200 OK", [("Content-Type", "application/json")])
            return [json.dumps(claims, indent=2).encode()]
        start_response("200 OK", [("Content-Type", "text/html")])
        return [b'<a href="/login">Login</a>']

    return app


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("OIDC_PORT", "0")))
    ap.add_argument("--demo", action="store_true")
    args = ap.parse_args()

    idp = None
    if args.demo:
        from cap_tpu.oidc.testing import TestProvider

        idp = TestProvider().start()
        issuer, client_id, client_secret, ca = (
            idp.issuer(), idp.client_id, idp.client_secret, idp.ca_cert())
    else:
        issuer = os.environ.get("OIDC_ISSUER", "")
        client_id = os.environ.get("OIDC_CLIENT_ID", "")
        client_secret = os.environ.get("OIDC_CLIENT_SECRET", "")
        ca = os.environ.get("OIDC_CA_PEM", "")
        if not issuer or not client_id:
            print("set OIDC_ISSUER and OIDC_CLIENT_ID (or use --demo)")
            return 2

    holder = {}
    server = make_server("127.0.0.1", args.port,
                         lambda e, s: holder["app"](e, s))
    server.RequestHandlerClass.log_message = lambda *a: None
    port = server.server_address[1]
    callback_url = f"http://127.0.0.1:{port}/callback"

    provider = Provider(Config(
        issuer=issuer, client_id=client_id, client_secret=client_secret,
        supported_signing_algs=["ES256", "RS256"],
        allowed_redirect_urls=[callback_url],
        provider_ca=ca or None,
    ))
    cache = RequestCache()
    holder["app"] = build_app(provider, cache, callback_url)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    print(f"SPA listening on http://localhost:{port} — visit /login")

    if args.demo:
        import urllib.request

        # a demo "browser": hit /login, follow redirects through the IdP
        # back to /callback, then fetch /success
        import ssl
        import urllib.error

        from cap_tpu.utils import http as _http

        ctx = _http.ssl_context_for_ca(ca)
        opener = urllib.request.build_opener(
            urllib.request.HTTPSHandler(context=ctx))
        resp = opener.open(f"http://127.0.0.1:{port}/login")
        final = resp.geturl()
        print("login round trip finished at:", final)
        body = opener.open(f"http://127.0.0.1:{port}{final[final.index('/success'):]}"
                           if "/success" in final else final).read()
        print("verified claims:", body.decode()[:200], "...")
        server.shutdown()
        idp.stop()
        return 0

    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
