CXX ?= g++
CXXFLAGS ?= -O3 -march=native -fPIC -shared -pthread -std=c++17 -Wall

NATIVE_DIR := cap_tpu/runtime/native
NATIVE_SO := $(NATIVE_DIR)/libcapruntime.so
CLIENT_DIR := cap_tpu/serve/native
CLIENT_SO := $(CLIENT_DIR)/libcapclient.so
PYTHON ?= python3
# ABI-tagged: must match what cap_tpu._build.EXT_NAME expects to load.
# A silent fallback name would build an artifact the loader never looks
# for, so a failed probe fails the claims target instead.
CLAIMS_EXT_NAME := $(shell $(PYTHON) -c "from cap_tpu._build import EXT_NAME; print(EXT_NAME)" 2>/dev/null)
PY_INCLUDE := $(shell $(PYTHON) -c "import sysconfig; print(sysconfig.get_paths()['include'])")

# CLAIMS_SO must be assigned BEFORE the `native:` rule below — make
# expands prerequisite lists at parse time, so a later assignment would
# leave the dependency empty and silently skip the claims build.
ifeq ($(CLAIMS_EXT_NAME),)
CLAIMS_SO := claims-probe-failed
.PHONY: claims-probe-failed
claims-probe-failed:
	@echo "error: could not import cap_tpu._build with PYTHON=$(PYTHON); claims extension name unknown" >&2; exit 1
else
CLAIMS_SO := $(NATIVE_DIR)/$(CLAIMS_EXT_NAME)
$(CLAIMS_SO): $(NATIVE_DIR)/claims_ext.cpp $(NATIVE_DIR)/claims_tape.h
	$(CXX) $(CXXFLAGS) -I$(PY_INCLUDE) -o $@ $<
endif

.PHONY: all native native-build test bench clean obs-smoke keyplane-smoke bench-trend mldsa-kat slhdsa-kat pallas-smoke claims-parity shm-smoke go-conformance check

all: native

native: $(NATIVE_SO) $(CLIENT_SO) $(CLAIMS_SO)

$(NATIVE_SO): $(NATIVE_DIR)/jose_native.cpp $(NATIVE_DIR)/serve_native.cpp \
		$(NATIVE_DIR)/telemetry_native.cpp $(NATIVE_DIR)/telemetry_native.h \
		$(NATIVE_DIR)/claims_validate.cpp $(NATIVE_DIR)/claims_tape.h \
		$(NATIVE_DIR)/shm_ring.cpp $(NATIVE_DIR)/shm_ring.h \
		$(NATIVE_DIR)/frontdoor_native.cpp $(NATIVE_DIR)/cvb1_wire.h
	$(CXX) $(CXXFLAGS) -o $@ $(filter %.cpp,$^)

$(CLIENT_SO): $(CLIENT_DIR)/client_native.cpp
	$(CXX) $(CXXFLAGS) -o $@ $<

# Force-rebuild the native runtime + client from source (gcc<11 CPUID
# fallback included — the SHA-NI probe that silently killed the whole
# .so in r11 compiles everywhere now) and fail LOUDLY if the serve
# chain's symbols don't resolve. tests/test_serve_native.py runs the
# same check as a tier-1 test so the native chain can't die silently.
native-build:
	rm -f $(NATIVE_SO) $(CLIENT_SO)
	$(MAKE) $(NATIVE_SO) $(CLIENT_SO)
	$(PYTHON) -c "import ctypes; lib = ctypes.CDLL('$(NATIVE_SO)'); \
	  [getattr(lib, s) for s in ('cap_prepare_batch', 'cap_serve_create', \
	   'cap_serve_add_conn', 'cap_serve_drain', 'cap_serve_post_results', \
	   'cap_serve_probe_frame', 'cap_bench_drive', 'cap_tel_create', \
	   'cap_tel_fold', 'cap_serve_post_results_tel', \
	   'cap_serve_ring_hwm', 'cap_claims_layout', \
	   'cap_claims_validate_batch', 'cap_frontdoor_create', \
	   'cap_frontdoor_add_conn', 'cap_frontdoor_commit', \
	   'cap_frontdoor_drain', 'cap_frontdoor_post_raw', \
	   'cap_frontdoor_probe_route')]; \
	  ctypes.CDLL('$(CLIENT_SO)').cap_client_connect; \
	  print('native-build: all serve-native symbols resolve')"

test: native
	python -m pytest tests/ -x -q

bench: native
	python bench.py

clean:
	rm -f $(NATIVE_SO) $(CLIENT_SO) $(NATIVE_DIR)/_capclaims*.so

test-all: native
	python -m pytest tests/ -q -m ""

golden-go:
	python tools/gen_go_golden.py

# Go conformance: the table-driven golden-frame sweep + the
# live-stub-worker suite (clients/go/captpu/conformance_test.go) when
# a Go toolchain exists; a LOUD skip otherwise — this image has none,
# so the committed golden vectors remain the cross-language pin
# (tests/test_conformance.py regenerates + byte-compares them).
go-conformance:
	@if command -v go >/dev/null 2>&1; then \
	  cd clients/go/captpu && go vet ./... && go test -v ./...; \
	else \
	  echo "SKIP go-conformance: no Go toolchain on this host -- install go >= 1.15 and re-run 'make go-conformance'"; \
	  echo "     (framing stays pinned by the golden vectors: tests/test_conformance.py + tools/gen_go_golden.py)"; \
	fi

# Observability smoke: boot a 2-worker stub fleet, scrape /metrics +
# /snapshot + /flight, fail on missing/NaN required gauges or a traced
# request that reached no flight recorder. Stub workers only — no jax
# import in the children, fits the tier-1 time budget.
obs-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) tools/obs_smoke.py

# Keyplane smoke: boot a 2-worker stub fleet, push 3 key epochs while
# mixed traffic flows, fail on missed convergence, any wrong verdict,
# a stale keyplane.epoch gauge, or an SLO breach (rotation lag /
# push-failure rate ride the default rules).
keyplane-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) tools/keyplane_smoke.py

# Bench regression sentinel: selftest the detector (synthetic series +
# a 15% regression injected into the real series must flag), then
# check the committed BENCH_r*/MULTICHIP_r* trajectory — fails when
# the latest round regresses any tracked metric >10% vs the best of
# the last 3 rounds.
bench-trend:
	$(PYTHON) tools/bench_trend.py --selftest
	$(PYTHON) tools/bench_trend.py

# ML-DSA known-answer gate: the pinned FIPS 204 KATs through all four
# verify surfaces (oracle / TPU both paths / serve / router) plus a
# randomized engine-vs-oracle parity selftest. Dependency-free.
mldsa-kat:
	JAX_PLATFORMS=cpu $(PYTHON) tools/mldsa_kat.py

# SLH-DSA known-answer gate: the pinned FIPS 205 KATs through the same
# four surfaces plus >=1k randomized engine-vs-oracle verifies per
# parameter set (CAP_SLHDSA_KAT_N overrides). Dependency-free; the
# heaviest check target (SLH-DSA verify is ~2-6k hashes/token).
slhdsa-kat:
	JAX_PLATFORMS=cpu $(PYTHON) tools/slhdsa_kat.py

# Kernel liveness gate: compile the fused Pallas NTT + Keccak kernels
# in interpret mode on the CPU backend and bit-check them against
# their refs (the native-build silent-death lesson applied to
# kernels). Missing Pallas stack -> loud skip with a counter.
pallas-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) tools/pallas_smoke.py

# Shared-memory transport smoke: boot one worker per available serve
# chain with transport=shm, drive it over the ring from the Python shm
# client, gate the serve.shm.* counters/gauges (attach negotiated,
# frames served, ZERO protocol errors) and the socket-fallback path.
shm-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) tools/shm_smoke.py

# Claims-rule differential gate: the generated ~1k adversarial corpus
# through the dict path, the raw-path Python rules, and the native
# claims engine (claims_validate.cpp) — verdicts and reason classes
# must be bit-identical, and every native status code must be
# exercised. Crypto-free, jax-free, fails if the engine won't load.
claims-parity: native
	JAX_PLATFORMS=cpu $(PYTHON) tools/claims_parity.py

# The default local CI gate: observability smoke + keyplane rotation
# smoke + perf-trend sentinel + post-quantum KAT gates (both
# families) + kernel liveness gate + claims-rule differential gate +
# shm-transport smoke + Go conformance (loud skip without a Go
# toolchain).
check: obs-smoke keyplane-smoke bench-trend mldsa-kat slhdsa-kat pallas-smoke claims-parity shm-smoke go-conformance
