CXX ?= g++
CXXFLAGS ?= -O3 -march=native -fPIC -shared -pthread -std=c++17 -Wall

NATIVE_DIR := cap_tpu/runtime/native
NATIVE_SO := $(NATIVE_DIR)/libcapruntime.so
CLAIMS_SO := $(NATIVE_DIR)/_capclaims.so
CLIENT_DIR := cap_tpu/serve/native
CLIENT_SO := $(CLIENT_DIR)/libcapclient.so
PYTHON ?= python3
PY_INCLUDE := $(shell $(PYTHON) -c "import sysconfig; print(sysconfig.get_paths()['include'])")

.PHONY: all native test bench clean

all: native

native: $(NATIVE_SO) $(CLIENT_SO) $(CLAIMS_SO)

$(NATIVE_SO): $(NATIVE_DIR)/jose_native.cpp
	$(CXX) $(CXXFLAGS) -o $@ $<

$(CLAIMS_SO): $(NATIVE_DIR)/claims_ext.cpp
	$(CXX) $(CXXFLAGS) -I$(PY_INCLUDE) -o $@ $<

$(CLIENT_SO): $(CLIENT_DIR)/client_native.cpp
	$(CXX) $(CXXFLAGS) -o $@ $<

test: native
	python -m pytest tests/ -x -q

bench: native
	python bench.py

clean:
	rm -f $(NATIVE_SO) $(CLIENT_SO) $(CLAIMS_SO)

test-all: native
	python -m pytest tests/ -q -m ""

golden-go:
	python tools/gen_go_golden.py
